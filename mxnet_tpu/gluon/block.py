"""Gluon Block / HybridBlock / CachedOp — the imperative model API.

Reference: ``python/mxnet/gluon/block.py`` + ``src/imperative/cached_op.cc``
(TBV — SURVEY.md §2.1, §3.1-3.2).

TPU redesign of hybridize (the keystone — SURVEY.md §7 phase 2):

- A non-hybridized HybridBlock runs op-by-op eagerly (each op is an XLA
  executable; correct but per-op dispatch overhead, like the reference's
  engine path).
- ``hybridize()`` swaps in a :class:`CachedOp`. Instead of tracing into an
  NNVM graph and replaying engine pushes, CachedOp **purifies** the forward:
  parameters and inputs become function arguments, parameter mutations during
  the trace (BatchNorm moving stats) become extra outputs, RNG draws fold a
  traced key — then the whole thing is ``jax.jit``-compiled once per
  (shapes, dtypes, train-mode) key. XLA fusion replaces both the reference's
  CachedOp static-alloc optimization and its memory planner.
- Under ``autograd.record``, the hybridized call is recorded as ONE tape op
  whose vjp is the vjp of the purified function — so ``loss.backward()``
  deposits directly into parameter ``.grad``s.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import jax
import numpy as np

from .. import autograd
from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from ..ndarray.ndarray import invoke
from ..ops.registry import OpDef
from .parameter import DeferredInitializationError, Parameter, ParameterDict

__all__ = ["Block", "HybridBlock", "SymbolBlock", "CachedOp"]


class _BlockScope(threading.local):
    def __init__(self):
        self.counters = {}

    def alloc_prefix(self, hint):
        n = self.counters.get(hint, 0)
        self.counters[hint] = n + 1
        return f"{hint}{n}_"


_SCOPE = _BlockScope()


def _flatten_nds(args):
    flat, fmt = [], []
    for a in args:
        if isinstance(a, NDArray):
            flat.append(a)
            fmt.append(None)
        elif isinstance(a, (list, tuple)):
            f, m = _flatten_nds(a)
            flat.extend(f)
            fmt.append((type(a), m))
        else:
            fmt.append(("const", a))
    return flat, fmt


def _unflatten_nds(flat_iter, fmt):
    out = []
    for f in fmt:
        if f is None:
            out.append(next(flat_iter))
        elif isinstance(f, tuple) and f[0] == "const":
            out.append(f[1])
        else:
            typ, m = f
            out.append(typ(_unflatten_nds(flat_iter, m)))
    return out


class Block:
    """Base class for all layers/models (reference gluon.Block)."""

    def __init__(self, prefix=None, params=None):
        cls = self.__class__.__name__.lower()
        self._prefix = prefix if prefix is not None else _SCOPE.alloc_prefix(cls)
        self._params = ParameterDict(self._prefix, shared=params)
        self._children = OrderedDict()
        self._reg_params = OrderedDict()  # attr name -> Parameter (direct)
        self._forward_hooks = []
        self._forward_pre_hooks = []

    # -- attribute magic: registering children and params ----------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
                self._params._params[value.name] = value
        super().__setattr__(name, value)

    # -- naming ----------------------------------------------------------
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix.rstrip("_")

    def name_scope(self):
        class _NS:
            def __enter__(s):
                return s

            def __exit__(s, *a):
                pass

        return _NS()

    # -- params ----------------------------------------------------------
    @property
    def params(self) -> ParameterDict:
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        pattern = re.compile(select) if select else None
        for p in self._iter_params():
            if pattern is None or pattern.match(p.name):
                ret._params[p.name] = p
        return ret

    def _iter_params(self):
        seen = set()
        for p in self._params.values():
            if id(p) not in seen:
                seen.add(id(p))
                yield p
        for c in self._children.values():
            for p in c._iter_params():
                if id(p) not in seen:
                    seen.add(id(p))
                    yield p

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init=init, ctx=ctx, force_reinit=force_reinit)

    def cast(self, dtype):
        for p in self._iter_params():
            p.cast(dtype)
        for c in self._children.values():
            pass  # params already covered recursively
        self._cast_hook(dtype)

    def _cast_hook(self, dtype):
        for c in self._children.values():
            c._cast_hook(dtype)

    # -- persistence ------------------------------------------------------
    def _collect_params_with_prefix(self, prefix=""):
        """Structural (attribute-path) parameter names, e.g. ``features.0.weight``
        — instance-independent, the format reference save_parameters uses
        (python/mxnet/gluon/block.py — TBV), unlike prefix names which embed
        a global construction counter."""
        if prefix:
            prefix += "."
        ret = {prefix + attr: p for attr, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        from ..ndarray import save as nd_save

        params = self._collect_params_with_prefix()
        nd_save(filename, {k: p.data() for k, p in params.items()
                           if p._data is not None})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        from ..ndarray import load as nd_load

        loaded = nd_load(filename)
        mine = self._collect_params_with_prefix()
        if loaded and mine and not any(k in mine for k in loaded):
            # fall back to prefix-name matching (older save format)
            mine = {p.name: p for p in self._iter_params()}
        for name, param in mine.items():
            if name in loaded:
                if param._data is None:
                    param.shape = loaded[name].shape
                    param.initialize(ctx=ctx)
                param.set_data(loaded[name])
            elif not allow_missing:
                raise KeyError(f"Parameter {name} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(mine)
            if extra:
                raise KeyError(f"{filename} contains extra parameters {sorted(extra)}")

    # -- call ------------------------------------------------------------
    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def __call__(self, *args, **kwargs):
        for h in self._forward_pre_hooks:
            h(self, args)
        out = self.forward(*args, **kwargs)
        for h in self._forward_hooks:
            h(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        for c in self._children.values():
            c.hybridize(active, **kwargs)

    def summary(self, *inputs):
        out = self(*inputs)
        lines = [f"{'Layer':<40}{'Params':>12}"]
        total = 0
        for p in self._iter_params():
            n = int(np.prod(p.shape)) if p.shape else 0
            total += n
            lines.append(f"{p.name:<40}{n:>12}")
        lines.append(f"{'TOTAL':<40}{total:>12}")
        print("\n".join(lines))
        return out

    def __repr__(self):
        kids = "\n".join(f"  ({k}): {v.__class__.__name__}" for k, v in self._children.items())
        return f"{self.__class__.__name__}(\n{kids}\n)"

    def apply(self, fn):
        for c in self._children.values():
            c.apply(fn)
        fn(self)
        return self


class HybridBlock(Block):
    """A Block that can be compiled (hybridized) into one XLA program."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False, **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape, **kwargs)
        self._cached_op = None
        super().hybridize(active, static_alloc=static_alloc, static_shape=static_shape,
                          **kwargs)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from input shapes. Built-in layers
        override; composite blocks resolve via their children during forward."""

    def _direct_param_kwargs(self):
        out = {}
        for attr, p in self._reg_params.items():
            out[attr] = p.data()
        return out

    def forward(self, x, *args, **kwargs):
        from ..symbol.symbol import Symbol

        if isinstance(x, Symbol):
            # symbolic tracing (reference: hybrid_forward receives F=symbol
            # when called with Symbols): parameters become named Variables,
            # children recurse through their own __call__ with Symbols.
            # Works for graphs whose hybrid_forward is F-generic and does
            # not inspect concrete .shape (the model-zoo CNN/MLP family).
            return self._forward_symbolic(x, *args, **kwargs)
        self._ensure_init(x, *args)
        if self._active:
            if any(p._data is None and p._deferred_init is not None
                   for p in self._iter_params()):
                # Deferred shapes must be resolved OUTSIDE the jit trace
                # (param init inside a trace would leak tracers): run this
                # first call eagerly, which initializes everything.
                return self._forward_eager(x, *args, **kwargs)
            if self._cached_op is None:
                self._cached_op = CachedOp(self)
            return self._cached_op(x, *args)
        return self._forward_eager(x, *args, **kwargs)

    def _forward_symbolic(self, x, *args, **kwargs):
        from .. import symbol as sym_mod

        def as_var(p):
            # carry the declared shape when fully known so the shared shape
            # pre-flight (analysis/shape_infer) — and hence Symbol.shape
            # inside shape-inspecting forwards — can anchor inference
            shape = getattr(p, "shape", None)
            if shape and all(int(d) > 0 for d in shape):
                return sym_mod.Variable(p.name, shape=tuple(shape))
            return sym_mod.Variable(p.name)

        params = {attr: as_var(p) for attr, p in self._reg_params.items()}
        return self.hybrid_forward(sym_mod, x, *args, **params, **kwargs)

    def _forward_eager(self, x, *args, **kwargs):
        from .. import ndarray as nd_mod

        try:
            params = self._direct_param_kwargs()
        except DeferredInitializationError:
            self.infer_shape(x, *args)
            params = self._direct_param_kwargs()
        return self.hybrid_forward(nd_mod, x, *args, **params, **kwargs)

    def _ensure_init(self, *args):
        """Resolve any deferred param shapes by probing children bottom-up."""
        for p in self._reg_params.values():
            if p._data is None and p._deferred_init is not None:
                self.infer_shape(*args)
                break

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def lint(self, shapes=None, passes=None, **shape_kwargs):
        """Static-analyze this block before any compilation.

        Runs the :class:`~mxnet_tpu.analysis.TraceLinter` source checks
        (concretization leaks in forward bodies) and — when the block
        traces symbolically — the full :class:`~mxnet_tpu.analysis.
        GraphLinter` over its graph with the given input shapes::

            report = net.lint(data=(2, 3, 32, 32))
            report.raise_if_errors()

        ``shapes`` maps input Variable names to shapes (one per positional
        forward input, in order). Blocks whose forward is not F-generic
        get an info-level ``not-symbolically-traceable`` finding and only
        the source checks.
        """
        from ..analysis import Finding, GraphLinter, Report, Severity, TraceLinter
        from .. import symbol as sym_mod

        all_shapes = dict(shapes or {})
        all_shapes.update({k: tuple(v) for k, v in shape_kwargs.items()})
        report = Report(TraceLinter().scan_source(self))
        ins = [sym_mod.Variable(n, shape=s) for n, s in all_shapes.items()]
        try:
            out = self(*ins) if ins else self(sym_mod.Variable("data"))
            if isinstance(out, (list, tuple)):
                out = sym_mod.Group(list(out))
        except Exception as e:
            report.add(Finding(
                "not-symbolically-traceable", Severity.INFO,
                f"block does not trace symbolically ({type(e).__name__}: "
                f"{str(e)[:200]}); graph passes skipped",
                node=getattr(self, "name", None),
                fix_hint="make hybrid_forward F-generic (ops via F, "
                         "F.split over tensor indexing) to enable graph "
                         "lint"))
            return report
        param_names = {p.name for p in self._iter_params()}
        report.extend(GraphLinter(passes=passes, param_names=param_names)
                      .lint(out, shapes=all_shapes))
        return report

    def export(self, path, epoch=0, format="json", example_inputs=None):
        """Save for deployment (reference HybridBlock.export — symbol.json +
        .params; reference serving analog: c_predict_api.cc — TBV).

        format="json" (default): params + a json descriptor.
        format="stablehlo": additionally serialize the full inference
        program (weights baked in as constants) via ``jax.export`` — the
        TPU-native deployment artifact standing in for ONNX/TensorRT.
        Requires ``example_inputs`` (tuple of NDArrays, or one NDArray)
        fixing the input shapes/dtypes. Reload with
        :func:`mxnet_tpu.gluon.load_stablehlo`.
        format="onnx": symbolically trace the block and write
        ``{path}-{epoch}.onnx`` via contrib.onnx.export_model (requires
        ``example_inputs`` for shapes; the block's graph must be in the
        exporter's covered op surface).
        """
        import json

        # normalize up front: the graph-embed below also counts inputs,
        # and a bare NDArray would make len() return its batch dimension
        if example_inputs is not None and \
                not isinstance(example_inputs, (list, tuple)):
            example_inputs = (example_inputs,)
        # validate BEFORE any file is written — a raise after
        # save_parameters would leave a truncated checkpoint on disk
        if format in ("onnx", "stablehlo"):
            if example_inputs is None:
                raise ValueError(f"{format} export needs example_inputs")
            deferred = [p.name for p in self._iter_params()
                        if p._data is None]
            if deferred:
                # exporting now would bake fresh initializer values into the
                # artifact — run one forward to resolve shapes first
                raise ValueError(
                    f"cannot export: parameters {deferred} have deferred "
                    "shapes; run a forward pass before export")

        self.save_parameters(f"{path}-{epoch:04d}.params")
        meta = {"format": "mxnet_tpu-hybrid", "class": self.__class__.__name__}
        # Embed the traced graph + a saved-name → variable-name map so the
        # artifact is servable (mxnet_tpu.serve.load) and reloadable as a
        # SymbolBlock without the original class. Best-effort: a block
        # whose forward is not F-generic exports params-only, as before.
        try:
            from .. import symbol as sym_mod

            n_inputs = len(example_inputs) if example_inputs is not None else 1
            data_syms = [sym_mod.Variable(f"data{i}" if i else "data")
                         for i in range(n_inputs)]
            traced = self(*data_syms)
            if isinstance(traced, (list, tuple)):
                traced = sym_mod.Group(list(traced))
            meta["symbol"] = traced.tojson()
            meta["param_map"] = {
                saved: p.name for saved, p in
                self._collect_params_with_prefix().items()}
        except Exception:  # noqa: BLE001 — tracing is optional here
            pass
        if format == "onnx":
            from .. import symbol as sym_mod
            from ..contrib.onnx import export_model

            data_syms = [sym_mod.Variable(f"data{i}" if i else "data")
                         for i in range(len(example_inputs))]
            sym = self(*data_syms)
            if isinstance(sym, (list, tuple)):
                raise ValueError(
                    f"onnx export supports single-output blocks; this one "
                    f"returns {len(sym)} outputs — export a wrapper that "
                    "selects one")
            params = {p.name: p.data() for p in self._iter_params()}
            onnx_path = f"{path}-{epoch:04d}.onnx"
            export_model(sym, params,
                         [tuple(x.shape) for x in example_inputs],
                         onnx_file_path=onnx_path)
            meta["onnx"] = onnx_path
            meta["input_shapes"] = [list(x.shape) for x in example_inputs]
        if format == "stablehlo":
            import jax
            from jax import export as jexport

            from ..parallel.functional import functionalize

            names, apply = functionalize(self, train=False)
            by_name = {p.name: p for p in self._iter_params()}
            param_vals = {n: by_name[n].data()._data for n in names}

            def infer(*xs):
                out, _aux = apply(param_vals, *xs)
                return out

            avals = [jax.ShapeDtypeStruct(x.shape, x.dtype)
                     for x in example_inputs]
            exported = jexport.export(jax.jit(infer))(*avals)
            blob = exported.serialize()
            with open(f"{path}-{epoch:04d}.stablehlo", "wb") as f:
                f.write(blob)
            meta["stablehlo"] = f"{path}-{epoch:04d}.stablehlo"
            meta["input_shapes"] = [list(x.shape) for x in example_inputs]
            meta["input_dtypes"] = [str(x.dtype) for x in example_inputs]
        with open(f"{path}-symbol.json", "w") as f:
            json.dump(meta, f)

    def optimize_for(self, *args, **kwargs):
        self.hybridize(True)


class CachedOp:
    """Purified + jitted forward of a HybridBlock (reference CachedOp analog).

    Cache key: (train_mode, param avals, input avals). Each entry holds a
    ``jax.jit``-compiled pure function
    ``fn(rng_key, *param_vals, *input_vals) -> (*outputs, *aux_updates)``
    where aux_updates are parameter mutations detected during tracing
    (e.g. BatchNorm moving stats).
    """

    def __init__(self, block: HybridBlock):
        self.block = block
        self._cache = {}
        # device-plane accounting (obs/device.py): one entry per compiled
        # cache entry, carrying XLA flops/bytes/HBM when capture is active
        self.compile_log = []

    def __call__(self, *inputs):
        flat_in, fmt = _flatten_nds(inputs)
        params = [p for p in self.block._iter_params() if p._data is not None]
        train = autograd.is_training()
        key = (
            train,
            tuple((p.data().shape, str(p.data().dtype)) for p in params),
            tuple((x.shape, str(x.dtype)) for x in flat_in),
        )
        entry = self._cache.get(key)
        if entry is None:
            entry = self._build(params, fmt, len(flat_in), train)
            self._cache[key] = entry
        rng = _new_rng()
        all_inputs = [NDArray(rng)] + [p.data() for p in params] + list(flat_in)
        result = invoke(entry["opdef"], all_inputs, {})
        if not isinstance(result, tuple):
            result = (result,)
        n_out = entry["n_out"]
        outs, aux = result[:n_out], result[n_out:]
        for p_idx, a in zip(entry["aux_param_idx"], aux):
            with autograd.pause():
                params[p_idx].data()._set_data(a._data)
        outs_it = iter(outs)
        restored = _unflatten_nds(outs_it, entry["out_fmt"])
        return restored[0] if len(restored) == 1 else tuple(restored)

    def _build(self, params, in_fmt, n_in, train):
        block = self.block
        n_params = len(params)
        aux_param_idx: list = []
        out_fmt_holder: list = []

        def raw_fn(rng_key, *vals):
            import jax.random as jr

            from .. import random as _random

            if hasattr(jr, "wrap_key_data") and rng_key.dtype == jax.numpy.uint32:
                rng_key = jr.wrap_key_data(rng_key)
            pvals = vals[:n_params]
            ivals = vals[n_params:]
            param_nds = [p.data() for p in params]
            saved = [(nd_._data, nd_._version) for nd_ in param_nds]
            try:
                for nd_, v in zip(param_nds, pvals):
                    nd_._data = v
                in_nds = _unflatten_nds(iter([NDArray(v) for v in ivals]), in_fmt)
                old_rec = autograd.set_recording(False)
                old_train = autograd.set_training(train)
                try:
                    with _random.trace_key_scope(rng_key):
                        out = block._forward_eager(*in_nds)
                finally:
                    autograd.set_recording(old_rec)
                    autograd.set_training(old_train)
                flat_out, fmt = _flatten_nds([out] if not isinstance(out, tuple) else list(out))
                out_fmt_holder.clear()
                out_fmt_holder.extend(fmt if not isinstance(out, tuple) else fmt)
                out_vals = [o._data for o in flat_out]
                # detect aux mutations (params whose wrapper was rebound)
                aux_vals = []
                aux_param_idx.clear()
                for i, (nd_, (old_data, _v)) in enumerate(zip(param_nds, saved)):
                    if nd_._data is not pvals[i]:
                        aux_param_idx.append(i)
                        aux_vals.append(nd_._data)
                return tuple(out_vals + aux_vals)
            finally:
                for nd_, (old_data, _v) in zip([p.data() for p in params], saved):
                    nd_._data = old_data

        jitted = jax.jit(raw_fn)

        # Trace once eagerly via jit lowering to populate out_fmt/aux metadata.
        # (jax.jit is lazy; we force trace with eval_shape on representative avals.)
        def trace_probe():
            import jax.numpy as jnp

            pav = [jax.ShapeDtypeStruct(p.data().shape, p.data().dtype) for p in params]
            rng_av = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
            # input avals come from the first real call; defer to call time
            return pav, rng_av

        opdef = OpDef(f"CachedOp_{block.name}", jitted,
                      num_outputs=lambda kw: None)  # resolved after first call

        entry = {"opdef": opdef, "aux_param_idx": aux_param_idx,
                 "out_fmt": out_fmt_holder, "n_out": None}

        # Wrap fn so first execution finalizes n_out/num_outputs metadata
        # (and, when device capture is active, AOT-compiles once for cost
        # accounting and keeps that executable for later calls).
        aot = {"compiled": None, "logged": False}

        def finalizing_fn(*vals, **kw):
            from .. import obs as _obs
            from .. import profiler as _profiler

            if _profiler.counting_dispatches():
                _profiler.count_dispatch("compiled")
            # Device-plane accounting only when capture is active (or
            # already produced an executable) — the disabled hot path must
            # not pay the per-call scans. A nested hybridized block's
            # CachedOp runs INSIDE its parent's trace: tracer args can't
            # feed an AOT executable (and there is no standalone program
            # to account), so only concrete calls capture/log and tracer
            # calls inline through the jit wrapper.
            fn = jitted
            if aot["compiled"] is not None or \
                    (not aot["logged"] and _obs.device.active()):
                concrete = not any(isinstance(v, jax.core.Tracer)
                                   for v in vals)
                if concrete and not aot["logged"]:
                    aot["logged"] = True
                    log_entry = {"block": block.name, "train": train,
                                 "avals": tuple(
                                     (tuple(v.shape),
                                      str(getattr(v, "dtype", "?")))
                                     for v in vals)}
                    compiled, cost = _obs.device.capture(
                        jitted, vals, site="cachedop", label=block.name,
                        kwargs=kw)
                    if compiled is not None:
                        aot["compiled"] = compiled
                    if cost:
                        log_entry.update(cost)
                    self.compile_log.append(log_entry)
                if concrete and aot["compiled"] is not None:
                    fn = aot["compiled"]
            res = fn(*vals, **kw)
            n_aux = len(aux_param_idx)
            entry["n_out"] = len(res) - n_aux
            return res

        opdef.fn = finalizing_fn
        opdef.num_outputs = lambda kw: len(out_fmt_holder) + len(aux_param_idx)
        return entry


def _new_rng():
    import jax.random as jr

    from .. import random as _random

    return jr.key_data(_random.next_key()) if hasattr(jr, "key_data") else _random.next_key()


class SymbolBlock(HybridBlock):
    """Run a Symbol graph as a Gluon block (reference SymbolBlock): free
    graph variables that aren't inputs become Parameters, so an exported
    ``symbol.json + .params`` pair reloads as a trainable/hybridizable
    block — the deployment-reload path (reference ``SymbolBlock.imports``).
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        if isinstance(outputs, (list, tuple)):
            if len(outputs) == 1:
                outputs = outputs[0]
            else:
                from ..symbol import Group

                outputs = Group(outputs)
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self._outputs_sym = outputs
        self._input_names = [i.name if hasattr(i, "name") else str(i)
                             for i in inputs]
        self._arg_names = [n for n in outputs.list_arguments()
                           if n not in self._input_names]
        self._aux_names = outputs.list_auxiliary_states()
        for n in self._arg_names:
            p = self.params.get(n, shape=(0,), allow_deferred_init=True)
            self._reg_params[n] = p
        for n in self._aux_names:
            p = self.params.get(n, shape=(0,), allow_deferred_init=True,
                                grad_req="null")
            self._reg_params[n] = p
        self._graph_fns = {}  # train flag -> (arg_names, aux_names, fn, _)

    def _direct_param_kwargs(self):
        return {}  # graph params are resolved by name in hybrid_forward

    def hybrid_forward(self, F, *args, **kwargs):
        from .. import autograd as ag
        from ..executor import _build_graph_fn
        from ..ndarray import NDArray
        from ..ndarray.ndarray import invoke_fn

        train = ag.is_training()
        entry = self._graph_fns.get(train)
        if entry is None:
            entry = self._graph_fns[train] = _build_graph_fn(
                self._outputs_sym, train=train)
        arg_names, aux_names, fn, _has_aux = entry
        by_name = dict(zip(self._input_names, args))
        ins = []
        for n in arg_names:
            v = by_name[n] if n in by_name else self.params.get(n).data()
            ins.append(v if isinstance(v, NDArray) else NDArray(v))
        aux_nds = [self.params.get(n).data() for n in aux_names]
        n_args = len(ins)

        # route through invoke_fn so eager calls land on the autograd tape
        # (fine-tuning an imported checkpoint with record()/backward works)
        def pure(*vals):
            outs, new_aux = fn(list(vals[:n_args]), list(vals[n_args:]))
            return tuple(outs) + tuple(new_aux[n] for n in aux_names)

        result = invoke_fn(pure, ins + aux_nds)
        result = result if isinstance(result, tuple) else (result,)
        n_out = len(result) - len(aux_names)
        outs, aux_new = result[:n_out], result[n_out:]
        with ag.pause():
            for nd_, new in zip(aux_nds, aux_new):
                nd_._set_data(new._data)
        return outs[0] if len(outs) == 1 else tuple(outs)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load an exported ``-symbol.json`` (+ ``.params``) into a block
        (reference SymbolBlock.imports; serving analog of MXPredCreate)."""
        from ..symbol import Variable, load as sym_load

        sym = sym_load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [Variable(n) for n in input_names]
        blk = SymbolBlock(sym, inputs)
        if param_file:
            from ..ndarray import load as nd_load

            loaded = nd_load(param_file)
            flat = {}
            for k, v in loaded.items():  # accept arg:/aux: checkpoint keys
                flat[k.split(":", 1)[1] if ":" in k else k] = v
            for n, p in blk._reg_params.items():
                if n in flat:
                    p.shape = flat[n].shape
                    p.initialize(ctx=ctx)
                    p.set_data(flat[n])
                else:
                    raise KeyError(f"parameter {n} missing in {param_file}")
        return blk


def load_stablehlo(path):
    """Load a ``HybridBlock.export(format="stablehlo")`` artifact as a
    callable ``fn(*inputs) -> NDArray`` (weights are baked into the
    program). The deployment-side counterpart of the reference's
    MXPredCreate/MXPredForward (c_predict_api — TBV)."""
    import jax
    from jax import export as jexport

    from ..ndarray import NDArray

    with open(path, "rb") as f:
        exported = jexport.deserialize(bytearray(f.read()))

    def fn(*inputs):
        vals = [x._data if isinstance(x, NDArray) else jax.numpy.asarray(x)
                for x in inputs]
        out = exported.call(*vals)
        if isinstance(out, (list, tuple)):
            return tuple(NDArray(o) for o in out)
        return NDArray(out)

    fn.exported = exported
    return fn
