"""Gluon Trainer — applies an optimizer to a set of Parameters.

Reference: ``python/mxnet/gluon/trainer.py`` (TBV — SURVEY.md §3.2): wires
grads to the KVStore (push/pull per key) then runs fused updates.

TPU redesign: with a single logical copy per parameter, ``_allreduce_grads``
is the KVStore hook only for multi-process (dist) kvstores; single-process
multi-chip DP happens inside the jitted step via psum (see kvstore/ and
parallel/). The step sequence (allreduce → update) and the public API
(step/allreduce_grads/update/save_states/load_states, update_on_kvstore)
match the reference.
"""
from __future__ import annotations

from .. import obs
from ..optimizer import Optimizer, Updater, create as opt_create
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None,
                 clip_global_norm=None):
        if hasattr(params, "values"):
            params = list(params.values())
        self._params = [p for p in params if p.grad_req != "null"]
        self._all_params = list(params)
        self._scale = 1.0
        optimizer_params = optimizer_params or {}
        self._optimizer = opt_create(optimizer, param_dict={
            i: p for i, p in enumerate(self._params)}, **optimizer_params)
        self._updaters = [Updater(self._optimizer)]
        self._kvstore_kind = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._params_to_init = list(self._params)
        # fused-update extensions: clip-by-global-norm is computed inside the
        # one-program update; amp.init_trainer attaches the loss scaler so
        # unscale + found-inf skip fuse into the same program
        self._clip_global_norm = clip_global_norm
        self._amp_loss_scaler = None
        self._health_monitor = None  # attach_health_monitor (obs/health.py)

    # ------------------------------------------------------------------
    @property
    def optimizer(self):
        return self._optimizer

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        from ..kvstore import create as kv_create

        kind = self._kvstore_kind
        if kind is None or (isinstance(kind, str) and kind in ("device", "local")):
            # single-process: no cross-process reduction needed — XLA collectives
            # handle intra-process multi-chip inside the jitted step.
            self._kvstore = None
        elif isinstance(kind, str):
            self._kvstore = kv_create(kind)
            for i, p in enumerate(self._params):
                self._kvstore.init(i, p.data())
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
        else:
            self._kvstore = kind
        if self._kvstore is not None and self._update_on_kvstore and \
                (self._clip_global_norm or self._amp_loss_scaler is not None):
            raise ValueError(
                "clip_global_norm / an attached AMP loss scaler are not "
                "supported with update_on_kvstore=True — the update runs "
                "server-side without them; set update_on_kvstore=False")
        self._kv_initialized = True

    # ------------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """rescale by 1/batch_size, allreduce, update (reference semantics)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        with obs.trace.span("allreduce"):
            self._allreduce_grads()
        with obs.trace.span("update"):
            self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        # one batched push/pull over all keys (the kvstore local-update path
        # then applies the whole batch as one fused program; dist stores get
        # their bulk-execution window)
        keys = list(range(len(self._params)))
        grads = [p.data().grad for p in self._params]
        self._kvstore.push(keys, grads)
        if not self._update_on_kvstore:
            self._kvstore.pull(keys, out=grads)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        with obs.trace.span("update"):
            self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        if self._health_monitor is not None:
            from ..obs import health as health_mod

            # stats variant only on sampled steps (cost amortizes 1/K)
            health_mod.request_stats(self._health_monitor.will_sample())
        if self._kvstore is not None and self._update_on_kvstore:
            keys = list(range(len(self._params)))
            self._kvstore.pull(keys, out=[p.data() for p in self._params])
            return
        idxs, grads, weights = [], [], []
        for i, p in enumerate(self._params):
            g = p.data().grad
            if g is None:
                if ignore_stale_grad:
                    continue
                raise RuntimeError(f"Parameter {p.name} has no grad")
            idxs.append(i)
            grads.append(g)
            weights.append(p.data())
        if idxs:
            # the whole parameter set updates as ONE compiled program
            # (optimizer/fused.py; MXNET_FUSED_UPDATE=0 = per-param oracle)
            updater.update_batch(idxs, grads, weights,
                                 loss_scaler=self._amp_loss_scaler,
                                 clip_global_norm=self._clip_global_norm)
        if self._health_monitor is not None:
            # sampled numerics telemetry + sentinel (docs/OBSERVABILITY.md
            # "Training health"); lr backoff applies in place — rollback
            # needs a checkpoint manager and stays with the owning loop
            self._health_monitor.step(
                engine=getattr(updater, "_engine", None),
                scaler=self._amp_loss_scaler,
                optimizer=self._optimizer)

    # ------------------------------------------------------------------
    def attach_health_monitor(self, monitor=True):
        """Attach the training-health sentinel (obs/health.py): ``True`` /
        a kwargs dict / a HealthMonitor; ``None`` detaches. While attached,
        the fused update program emits device-resident numerics stats and
        ``step()`` feeds the sampled sentinel; record the per-batch loss
        with ``monitor.record_loss(loss)`` (the estimator's HealthHandler
        does). Returns the monitor."""
        from ..obs import health as health_mod

        if self._health_monitor is not None:
            health_mod.deactivate()
            health_mod.request_stats(None)
            self._health_monitor = None
        mon = health_mod.as_monitor(monitor)
        if mon is not None:
            if mon.param_names is None:
                mon.attach_names([p.name for p in self._params])
            health_mod.activate()
            self._health_monitor = mon
        return mon

    # ------------------------------------------------------------------
    def save_states(self, fname):
        from ..checkpoint.atomic import atomic_write_bytes

        # atomic: a crash mid-save must not leave a truncated .states file
        atomic_write_bytes(fname, self._updaters[0].get_states())

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updaters[0].set_states(f.read())

    # -- checkpoint/resume hooks (docs/ROBUSTNESS.md) ----------------------
    def get_checkpoint_state(self):
        """Full optimizer snapshot for a CheckpointManager: slot arrays plus
        the scalar counters ``save_states`` loses (num_update and the
        per-index counts that drive Adam/Nadam bias correction)."""
        from ..checkpoint.state import capture_optimizer

        arrays = {}
        meta = capture_optimizer(self._updaters[0], self._optimizer, arrays)
        return {"arrays": arrays, "optimizer": meta}

    def set_checkpoint_state(self, state):
        from ..checkpoint.state import TrainingState, restore_optimizer

        restore_optimizer(self._updaters[0], self._optimizer,
                          TrainingState(state["arrays"],
                                        {"optimizer": state["optimizer"]}))
