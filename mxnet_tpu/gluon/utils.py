"""Gluon utilities: split_and_load, clip_global_norm, download stub.

Reference: ``python/mxnet/gluon/utils.py`` (TBV — SURVEY.md §2.4 DP row).
On TPU, `split_and_load` exists for script compat; the idiomatic path shards
one global batch over the Mesh via jax.sharding instead of a python-side split.
"""
from __future__ import annotations

import math

from ..context import Context
from ..ndarray import NDArray, array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1", "download"]


def split_data(data: NDArray, num_slice: int, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"batch size {size} not divisible by number of slices {num_slice}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(axis=batch_axis, begin=begin, end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(c) for s, c in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays in place so their global L2 norm ≤ max_norm."""
    import numpy as np

    total = 0.0
    for a in arrays:
        n = float(a.norm().asscalar())
        total += n * n
    total = math.sqrt(total)
    if check_isfinite and not np.isfinite(total):
        import warnings

        warnings.warn("nan or inf found in clip_global_norm")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total


def check_sha1(filename, sha1_hash):
    import hashlib

    h = hashlib.sha1()
    with open(filename, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise RuntimeError("download() unavailable: this environment has no egress; "
                       "place files locally instead")
