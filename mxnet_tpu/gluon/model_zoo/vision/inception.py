"""Inception v3 (reference gluon/model_zoo/vision/inception.py — TBV)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _make_basic_conv(**kwargs):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Branches(HybridBlock):
    """Run children in parallel on the same input and concat on channels."""

    def __init__(self, branches, **kwargs):
        super().__init__(**kwargs)
        for i, b in enumerate(branches):
            self.register_child(b, f"branch{i}")

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self._children.values()], dim=1)


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential()
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    for channels, kernel, stride, pad in conv_settings:
        kw = {"channels": channels, "kernel_size": kernel}
        if stride:
            kw["strides"] = stride
        if pad is not None:
            kw["padding"] = pad
        out.add(_make_basic_conv(**kw))
    return out


def _make_A(pool_features):
    return _Branches([
        _make_branch(None, (64, 1, None, None)),
        _make_branch(None, (48, 1, None, None), (64, 5, None, 2)),
        _make_branch(None, (64, 1, None, None), (96, 3, None, 1), (96, 3, None, 1)),
        _make_branch("avg", (pool_features, 1, None, None)),
    ])


def _make_B():
    return _Branches([
        _make_branch(None, (384, 3, 2, None)),
        _make_branch(None, (64, 1, None, None), (96, 3, None, 1), (96, 3, 2, None)),
        _make_branch("max"),
    ])


def _make_C(channels_7x7):
    return _Branches([
        _make_branch(None, (192, 1, None, None)),
        _make_branch(None, (channels_7x7, 1, None, None),
                     (channels_7x7, (1, 7), None, (0, 3)),
                     (192, (7, 1), None, (3, 0))),
        _make_branch(None, (channels_7x7, 1, None, None),
                     (channels_7x7, (7, 1), None, (3, 0)),
                     (channels_7x7, (1, 7), None, (0, 3)),
                     (channels_7x7, (7, 1), None, (3, 0)),
                     (192, (1, 7), None, (0, 3))),
        _make_branch("avg", (192, 1, None, None)),
    ])


def _make_D():
    return _Branches([
        _make_branch(None, (192, 1, None, None), (320, 3, 2, None)),
        _make_branch(None, (192, 1, None, None), (192, (1, 7), None, (0, 3)),
                     (192, (7, 1), None, (3, 0)), (192, 3, 2, None)),
        _make_branch("max"),
    ])


def _make_E():
    return _Branches([
        _make_branch(None, (320, 1, None, None)),
        _SplitConcat(_make_basic_conv(channels=384, kernel_size=1),
                     [_make_basic_conv(channels=384, kernel_size=(1, 3),
                                       padding=(0, 1)),
                      _make_basic_conv(channels=384, kernel_size=(3, 1),
                                       padding=(1, 0))]),
        _SplitConcat(
            _seq(_make_basic_conv(channels=448, kernel_size=1),
                 _make_basic_conv(channels=384, kernel_size=3, padding=1)),
            [_make_basic_conv(channels=384, kernel_size=(1, 3), padding=(0, 1)),
             _make_basic_conv(channels=384, kernel_size=(3, 1), padding=(1, 0))]),
        _make_branch("avg", (192, 1, None, None)),
    ])


def _seq(*blocks):
    out = nn.HybridSequential()
    out.add(*blocks)
    return out


class _SplitConcat(HybridBlock):
    """stem -> [branch_a, branch_b] -> concat (Inception-E fan-out)."""

    def __init__(self, stem, branches, **kwargs):
        super().__init__(**kwargs)
        self.stem = stem
        for i, b in enumerate(branches):
            self.register_child(b, f"split{i}")
        self._n = len(branches)

    def hybrid_forward(self, F, x):
        x = self.stem(x)
        outs = [self._children[f"split{i}"](x) for i in range(self._n)]
        return F.concat(*outs, dim=1)


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(_make_basic_conv(channels=32, kernel_size=3, strides=2))
        self.features.add(_make_basic_conv(channels=32, kernel_size=3))
        self.features.add(_make_basic_conv(channels=64, kernel_size=3, padding=1))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_basic_conv(channels=80, kernel_size=1))
        self.features.add(_make_basic_conv(channels=192, kernel_size=3))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_make_E())
        self.features.add(_make_E())
        self.features.add(nn.AvgPool2D(pool_size=8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable (no network)")
    return Inception3(**kwargs)
