"""Gluon — the imperative high-level API (reference python/mxnet/gluon/)."""
from .block import Block, HybridBlock, SymbolBlock, load_stablehlo  # noqa: F401
from .parameter import Parameter, Constant, ParameterDict  # noqa: F401
from .trainer import Trainer  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import utils  # noqa: F401


def __getattr__(name):
    import importlib

    if name in ("rnn", "data", "model_zoo", "contrib"):
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute {name!r}")
