"""Datasets (reference python/mxnet/gluon/data/dataset.py — TBV SURVEY.md §2.3)."""
from __future__ import annotations

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset: __getitem__ + __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        from .sampler import FilterSampler

        sampler = FilterSampler(fn, self)
        return _SampledDataset(self, sampler)

    def shard(self, num_shards, index):
        """Rank-sharding (the part_index/num_parts analog for dist data)."""
        assert 0 <= index < num_shards
        length = len(self)
        per = length // num_shards
        rest = length % num_shards
        start = per * index + min(index, rest)
        end = start + per + (1 if index < rest else 0)
        return _SampledDataset(self, list(range(start, end)))

    def take(self, count):
        return _SampledDataset(self, list(range(min(count, len(self)))))

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _SampledDataset(Dataset):
    def __init__(self, dataset, sampler):
        self._dataset = dataset
        self._indices = list(sampler)

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, idx):
        return self._dataset[self._indices[idx]]


class ArrayDataset(Dataset):
    """Zip of equal-length arrays/datasets."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            assert len(a) == self._length, "all arrays must have the same length"
            self._data.append(a)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference gluon.data.RecordFileDataset)."""

    def __init__(self, filename):
        from ...io.recordio import MXIndexedRecordIO
        import os

        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
