"""DataLoader — batched, shuffled, prefetching loader.

Reference: ``python/mxnet/gluon/data/dataloader.py`` (multiprocessing workers
feeding a shared-memory queue — TBV SURVEY.md §2.3).

TPU redesign: the reference forks worker *processes* because CPython + CUDA
pinned-memory copies benefit from process isolation. Here workers are a
thread pool with a bounded prefetch window: decode/augment is numpy/PIL work
that releases the GIL, host→device transfer is async under PJRT, and forking
after the JAX runtime initializes is unsafe. The observable API (num_workers,
batchify_fn, last_batch, pin_memory) is kept; ``num_workers=0`` is fully
synchronous like the reference.
"""
from __future__ import annotations

import concurrent.futures as _futures

import numpy as np

from ...ndarray import NDArray, array as nd_array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        from ... import ndarray as nd

        return nd.stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    if arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    return nd_array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None, thread_pool=False,
                 timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size is required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with an explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise ValueError("batch_sampler is mutually exclusive with "
                             "batch_size/shuffle/sampler/last_batch")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, int(num_workers))
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return

        with _futures.ThreadPoolExecutor(self._num_workers) as pool:
            pending = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch):
                    pending.append(pool.submit(self._make_batch, next(it)))
            except StopIteration:
                pass
            while pending:
                fut = pending.pop(0)
                try:
                    pending.append(pool.submit(self._make_batch, next(it)))
                except StopIteration:
                    pass
                yield fut.result()

    def __len__(self):
        return len(self._batch_sampler)
