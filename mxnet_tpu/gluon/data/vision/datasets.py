"""Vision datasets (reference python/mxnet/gluon/data/vision/datasets.py — TBV).

The reference auto-downloads; this environment has zero egress, so datasets
read the standard on-disk formats (IDX for MNIST, the python pickle batches
for CIFAR) from ``root`` and fail with a clear message when absent.
``SyntheticImageDataset`` is the benchmark stand-in.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import warnings

import numpy as np

from ....ndarray import array as nd_array
from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100", "ImageFolderDataset",
           "ImageRecordDataset", "SyntheticImageDataset"]


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    zeros, dtype_code, ndim = struct.unpack(">HBB", data[:4])
    dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
    dtype = {8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32,
             13: np.float32, 14: np.float64}[dtype_code]
    return np.frombuffer(data, dtype=dtype, offset=4 + 4 * ndim).reshape(dims)


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        x = nd_array(self._data[idx])
        y = int(self._label[idx])
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from IDX files in ``root`` (train-images-idx3-ubyte[.gz] etc)."""

    _base = "train"
    _files = {True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
              False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")}

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _find(self, stem):
        for cand in (stem, stem + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(
            f"{stem}[.gz] not found under {self._root}; this environment has no "
            f"network access — place the IDX files there, or use "
            f"SyntheticImageDataset for smoke tests")

    def _get_data(self):
        img, lbl = self._files[self._train]
        images = _read_idx(self._find(img))
        labels = _read_idx(self._find(lbl))
        self._data = images.reshape(-1, 28, 28, 1)
        self._label = labels.astype(np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the python pickle batches under ``root``."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _batches(self):
        if self._train:
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        xs, ys = [], []
        for name in self._batches():
            path = None
            for sub in ("", "cifar-10-batches-py", "cifar-100-python"):
                cand = os.path.join(self._root, sub, name)
                if os.path.exists(cand):
                    path = cand
                    break
            if path is None:
                raise FileNotFoundError(
                    f"{name} not found under {self._root}; no network access — "
                    f"place the CIFAR python batches there, or use "
                    f"SyntheticImageDataset")
            with open(path, "rb") as f:
                batch = pickle.load(f, encoding="latin1")
            xs.append(np.asarray(batch["data"], np.uint8).reshape(-1, 3, 32, 32))
            ys.append(np.asarray(batch.get("labels", batch.get("fine_labels")),
                                 np.int32))
        self._data = np.concatenate(xs).transpose(0, 2, 3, 1)  # NHWC like reference
        self._label = np.concatenate(ys)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=True, train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _batches(self):
        return ["train"] if self._train else ["test"]


class SyntheticImageDataset(Dataset):
    """Deterministic fake images+labels — the zero-egress benchmark feed
    (stands in where reference benchmarks use ``--benchmark 1`` synthetic
    data in example/image-classification/common/data.py)."""

    def __init__(self, length=1024, shape=(3, 224, 224), num_classes=1000,
                 layout="CHW", seed=0):
        self._length = length
        self._shape = tuple(shape)
        self._classes = num_classes
        self._seed = seed

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        rng = np.random.RandomState((self._seed * 1_000_003 + idx) % (2 ** 31))
        img = rng.randint(0, 256, size=self._shape).astype(np.float32) / 255.0
        label = int(rng.randint(self._classes))
        return nd_array(img), label


class ImageFolderDataset(Dataset):
    """root/class_x/xxx.jpg layout; decodes with PIL (reference uses mx.image)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                warnings.warn(f"ignoring {path}, not a directory")
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if os.path.splitext(fname)[1].lower() in (".jpg", ".jpeg", ".png",
                                                          ".bmp"):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from PIL import Image

        path, label = self.items[idx]
        img = Image.open(path)
        img = img.convert("RGB" if self._flag else "L")
        arr = np.asarray(img, np.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        x = nd_array(arr)
        if self._transform is not None:
            return self._transform(x, label)
        return x, label


class ImageRecordDataset(Dataset):
    """Dataset over an image RecordIO file (reference ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ....io.recordio import MXIndexedRecordIO, unpack_img
        import os as _os

        self._unpack_img = unpack_img
        idx_file = _os.path.splitext(filename)[0] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        record = self._record.read_idx(self._record.keys[idx])
        header, img = self._unpack_img(record, iscolor=self._flag)
        x = nd_array(img)
        label = header.label
        if self._transform is not None:
            return self._transform(x, label)
        return x, label
