"""Vision transforms (reference python/mxnet/gluon/data/vision/transforms.py —
TBV). HWC uint8/float in, per-sample host-side ops: these run in DataLoader
workers on numpy (the device-side equivalents live in mx.image / image ops)."""
from __future__ import annotations

import numpy as np

from ....ndarray import NDArray, array as nd_array
from ...block import Block, HybridBlock
from ...nn.basic_layers import Sequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomLighting", "RandomColorJitter"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class Compose(Sequential):
    """Sequentially compose transforms."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference ToTensor)."""

    def forward(self, x):
        arr = _to_np(x).astype(np.float32) / 255.0
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return nd_array(arr)


class Normalize(Block):
    """(x - mean) / std on CHW float input."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        arr = _to_np(x)
        return nd_array((arr - self._mean) / self._std)


def _resize_np(arr, size, interp="bilinear"):
    from PIL import Image

    if isinstance(size, int):
        size = (size, size)
    h, w = arr.shape[:2]
    if (w, h) == tuple(size):
        return arr
    mode = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
            "bicubic": Image.BICUBIC}[interp]
    squeeze = arr.shape[-1] == 1
    img = Image.fromarray(arr.squeeze(-1) if squeeze else arr.astype(np.uint8))
    out = np.asarray(img.resize(tuple(size), mode))
    if squeeze:
        out = out[:, :, None]
    return out


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation="bilinear"):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        arr = _to_np(x)
        size = self._size
        if self._keep and isinstance(size, int):
            h, w = arr.shape[:2]
            if h < w:
                size = (int(w * size / h), size)
            else:
                size = (size, int(h * size / w))
        return nd_array(_resize_np(arr, size, self._interp))


class CenterCrop(Block):
    def __init__(self, size, interpolation="bilinear"):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interp = interpolation

    def forward(self, x):
        arr = _to_np(x)
        w, h = self._size
        H, W = arr.shape[:2]
        if H < h or W < w:
            arr = _resize_np(arr, (max(w, W), max(h, H)), self._interp)
            H, W = arr.shape[:2]
        y0, x0 = (H - h) // 2, (W - w) // 2
        return nd_array(arr[y0:y0 + h, x0:x0 + w])


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation="bilinear"):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._pad = pad
        self._interp = interpolation

    def forward(self, x):
        arr = _to_np(x)
        if self._pad:
            p = self._pad
            arr = np.pad(arr, ((p, p), (p, p), (0, 0)), mode="constant")
        w, h = self._size
        H, W = arr.shape[:2]
        if H < h or W < w:
            arr = _resize_np(arr, (max(w, W), max(h, H)), self._interp)
            H, W = arr.shape[:2]
        y0 = np.random.randint(0, H - h + 1)
        x0 = np.random.randint(0, W - w + 1)
        return nd_array(arr[y0:y0 + h, x0:x0 + w])


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        arr = _to_np(x)
        H, W = arr.shape[:2]
        area = H * W
        for _ in range(10):
            target = np.random.uniform(*self._scale) * area
            ratio = np.exp(np.random.uniform(np.log(self._ratio[0]),
                                             np.log(self._ratio[1])))
            w = int(round(np.sqrt(target * ratio)))
            h = int(round(np.sqrt(target / ratio)))
            if w <= W and h <= H:
                x0 = np.random.randint(0, W - w + 1)
                y0 = np.random.randint(0, H - h + 1)
                crop = arr[y0:y0 + h, x0:x0 + w]
                return nd_array(_resize_np(crop, self._size, self._interp))
        return nd_array(_resize_np(arr, self._size, self._interp))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        arr = _to_np(x)
        if np.random.rand() < 0.5:
            arr = arr[:, ::-1]
        return nd_array(np.ascontiguousarray(arr))


class RandomFlipTopBottom(Block):
    def forward(self, x):
        arr = _to_np(x)
        if np.random.rand() < 0.5:
            arr = arr[::-1]
        return nd_array(np.ascontiguousarray(arr))


class _RandomJitter(Block):
    def __init__(self, amount):
        super().__init__()
        self._amount = amount

    def _factor(self):
        return 1.0 + np.random.uniform(-self._amount, self._amount)


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        return nd_array(_to_np(x).astype(np.float32) * self._factor())


class RandomContrast(_RandomJitter):
    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        mean = arr.mean()
        return nd_array(mean + (arr - mean) * self._factor())


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        gray = arr.mean(axis=-1, keepdims=True)
        return nd_array(gray + (arr - gray) * self._factor())


class RandomLighting(Block):
    """AlexNet-style PCA noise."""

    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha=0.1):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        alpha = np.random.normal(0, self._alpha, 3).astype(np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd_array(arr + rgb)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))

    def forward(self, x):
        ts = list(self._ts)
        np.random.shuffle(ts)
        for t in ts:
            x = t(x)
        return x
