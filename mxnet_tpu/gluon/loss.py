"""Gluon losses.

Reference: ``python/mxnet/gluon/loss.py`` (TBV — SURVEY.md §2.3). Semantics
kept: per-sample weighting, batch_axis mean, sample_weight broadcasting.
"""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss", "KLDivLoss",
           "HuberLoss", "HingeLoss", "SquaredHingeLoss", "LogisticLoss",
           "TripletLoss", "CosineEmbeddingLoss", "CTCLoss",
           "PoissonNLLLoss", "SDMLLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None and weight != 1.0:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{self.__class__.__name__}(batch_axis={self._batch_axis}, w={self._weight})"


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SoftmaxCrossEntropyLoss(Loss):
    """CE with integrated log-softmax (reference SoftmaxCrossEntropyLoss)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False, weight=None,
                 batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            # max(x,0) - x*z + log(1+exp(-|x|)) — numerically stable
            loss = F.relu(pred) - pred * label + \
                F.Activation(-F.abs(pred), act_type="softrelu")
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label + F.log(1 - pred + eps) * (1 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._fmt = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._fmt == "binary":
            label = 2 * label - 1
        loss = F.Activation(-pred * label, act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(pred - positive) - F.square(pred - negative),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        def cos(a, b):
            num = F.sum(a * b, axis=-1)
            den = F.sqrt(F.sum(a * a, axis=-1)) * F.sqrt(F.sum(b * b, axis=-1))
            return num / (den + 1e-12)

        sim = cos(input1, input2)
        label = label.reshape(sim.shape)
        loss = F.where(label == 1, 1 - sim, F.relu(sim - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification loss (reference contrib.CTCLoss).

    Implemented with the stable log-alpha dynamic program via lax.scan.
    Layout: pred (T, N, C) unless layout='NTC'; labels (N, L) padded with -1.
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None, label_lengths=None,
                       sample_weight=None):
        from ..ndarray.ndarray import invoke_fn

        ntc = self._layout == "NTC"

        def ctc(pred_, label_):
            import jax.numpy as jnp
            from jax import lax

            x = pred_ if not ntc else jnp.swapaxes(pred_, 0, 1)  # (T, N, C)
            T, N, C = x.shape
            logp = x - jnp.max(x, axis=-1, keepdims=True)
            logp = logp - jnp.log(jnp.sum(jnp.exp(logp), axis=-1, keepdims=True))
            lab = label_.astype(jnp.int32)  # (N, L), -1 or 0 padding
            L = lab.shape[1]
            valid = lab > 0  # blank index 0, padding <=0
            lab_len = jnp.sum(valid.astype(jnp.int32), axis=1)
            S = 2 * L + 1
            ext = jnp.zeros((N, S), jnp.int32)
            ext = ext.at[:, 1::2].set(jnp.where(valid, lab, 0))
            neg_inf = -1e30
            a0 = jnp.full((N, S), neg_inf)
            a0 = a0.at[:, 0].set(logp[0, :, 0])
            a0 = a0.at[:, 1].set(jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0])

            def step(alpha, logp_t):
                prev1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], 1)
                prev2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], 1)
                idx = jnp.arange(S)[None, :]
                same = jnp.concatenate(
                    [jnp.zeros((N, 2), bool),
                     ext[:, 2:] == ext[:, :-2]], 1)
                allow2 = (idx % 2 == 1) & (~same)
                m = jnp.maximum(alpha, prev1)
                m = jnp.where(allow2, jnp.maximum(m, prev2), m)
                s = (jnp.exp(alpha - m) + jnp.exp(prev1 - m)
                     + jnp.where(allow2, jnp.exp(prev2 - m), 0.0))
                new = m + jnp.log(jnp.maximum(s, 1e-38))
                emit = jnp.take_along_axis(logp_t, ext, axis=1)
                return new + emit, None

            alphaT, _ = lax.scan(step, a0, logp[1:])
            end1 = 2 * lab_len
            end2 = jnp.maximum(2 * lab_len - 1, 0)
            lse = jnp.logaddexp(
                jnp.take_along_axis(alphaT, end1[:, None], 1)[:, 0],
                jnp.take_along_axis(alphaT, end2[:, None], 1)[:, 0])
            return -lse

        loss = invoke_fn(ctc, [pred, label])
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    """Poisson negative log likelihood (reference gluon.loss.PoissonNLLLoss):
    pred is the predicted MEAN (or its log when from_logits=True)."""

    def __init__(self, weight=1.0, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       epsilon=1e-08):
        label = _reshape_like(F, label, pred)
        if self._from_logits:
            loss = F.exp(pred) - label * pred
        else:
            loss = pred - label * F.log(pred + epsilon)
        if self._compute_full:
            # Stirling approximation of log(label!) for label > 1
            stirling = (label * F.log(label + epsilon) - label
                        + 0.5 * F.log(2.0 * 3.141592653589793
                                      * (label + epsilon)))
            loss = loss + F.where(label > 1.0, stirling,
                                  F.zeros_like(label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference gluon.loss.SDMLLoss):
    row i scores x1[i] against every x2[j] by negative euclidean distance;
    the matching pair j==i is the target class with label smoothing spread
    over the non-matching candidates."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._smooth = smoothing_parameter

    def hybrid_forward(self, F, x1, x2, sample_weight=None):
        import jax
        import jax.numpy as jnp

        from ..ndarray.ndarray import invoke_fn

        def pure(a, b):
            n = a.shape[0]
            d = jnp.sqrt(jnp.sum((a[:, None, :] - b[None, :, :]) ** 2,
                                 axis=-1) + 1e-12)
            logits = -d                                    # (N, N)
            logp = logits - jax.scipy.special.logsumexp(logits, axis=1,
                                                        keepdims=True)
            eye = jnp.eye(n, dtype=logits.dtype)
            target = (eye * (1.0 - self._smooth)
                      + (1.0 - eye) * (self._smooth / (n - 1)))
            return -jnp.mean(jnp.sum(target * logp, axis=1))

        return invoke_fn(pure, [x1, x2])
