"""Gluon basic layers: Dense, Activation, Dropout, BatchNorm, LayerNorm,
Embedding, Flatten, InstanceNorm, GroupNorm, Sequential containers.

Reference: ``python/mxnet/gluon/nn/basic_layers.py`` (TBV — SURVEY.md §2.3).
"""
from __future__ import annotations

import numpy as np

from ... import autograd
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation", "Dropout",
           "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "Embedding",
           "Flatten", "Lambda", "HybridLambda", "ELU", "SELU", "PReLU", "GELU",
           "Swish", "SiLU", "LeakyReLU"]


class Sequential(Block):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x, *args):
        for b in self._children.values():
            x = b(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def infer_shape(self, *args):
        # Run children eagerly so each resolves its own deferred params.
        x = args[0]
        for b in self._children.values():
            x = b(x)

    def hybrid_forward(self, F, x):
        for b in self._children.values():
            x = b(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference gluon.nn.Dense over FullyConnected op)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act = activation
        self.weight = self.params.get("weight", shape=(units, in_units), dtype=dtype,
                                      init=weight_initializer, allow_deferred_init=True)
        self.bias = self.params.get("bias", shape=(units,), dtype=dtype,
                                    init=bias_initializer) if use_bias else None

    def infer_shape(self, x, *args):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape_inferred((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, *( [bias] if bias is not None else []),
                               num_hidden=self._units, no_bias=bias is None,
                               flatten=self._flatten)
        if self._act:
            out = F.Activation(out, act_type=self._act)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act = activation

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf", **kwargs):
        super().__init__(**kwargs)
        self._approx = approximation

    def hybrid_forward(self, F, x):
        return F.gelu(x, approximation=self._approx)


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class SiLU(Swish):
    """beta=1 Swish under its 2.x name."""

    def __init__(self, **kwargs):
        super().__init__(beta=1.0, **kwargs)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer="constant", in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod

        self.alpha = self.params.get("alpha", shape=(in_channels,),
                                     init=init_mod.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Lambda(Block):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        self._fn = function

    def forward(self, *args):
        from ... import ndarray as nd_mod

        if isinstance(self._fn, str):
            return getattr(nd_mod, self._fn)(*args)
        return self._fn(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        self._fn = function

    def hybrid_forward(self, F, *args):
        if isinstance(self._fn, str):
            return getattr(F, self._fn)(*args)
        return self._fn(F, *args)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                      dtype=dtype, init=weight_initializer)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class BatchNorm(HybridBlock):
    """Batch normalization with moving-average running stats.

    The running stats are updated functionally: the BatchNorm op returns batch
    mean/var (output_mean_var=True) and this layer folds them into the
    moving averages — inside CachedOp traces that mutation becomes an extra
    jit output assigned back after each step.
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        sh = (in_channels,)
        self.gamma = self.params.get("gamma", shape=sh, init=gamma_initializer,
                                     allow_deferred_init=True,
                                     differentiable=scale)
        self.beta = self.params.get("beta", shape=sh, init=beta_initializer,
                                    allow_deferred_init=True,
                                    differentiable=center)
        self.running_mean = self.params.get("running_mean", shape=sh,
                                            init=running_mean_initializer,
                                            allow_deferred_init=True,
                                            differentiable=False)
        self.running_var = self.params.get("running_var", shape=sh,
                                           init=running_variance_initializer,
                                           allow_deferred_init=True,
                                           differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape_inferred((c,))

    def _bn_op(self, F):
        """Overridable hook: (op, extra kwargs). SyncBatchNorm swaps in the
        cross-device op without duplicating the stats-folding logic below."""
        return F.BatchNorm, {}

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        train = autograd.is_training()
        op, extra = self._bn_op(F)
        if train and not self._use_global_stats:
            out, mean, var = op(
                x, gamma, beta, running_mean, running_var, eps=self._eps,
                momentum=self._momentum, fix_gamma=not self._scale,
                use_global_stats=False, output_mean_var=True, axis=self._axis,
                **extra)
            m = self._momentum
            with autograd.pause():
                self.running_mean.data()._set_data(
                    (m * running_mean + (1 - m) * mean.detach())._data)
                self.running_var.data()._set_data(
                    (m * running_var + (1 - m) * var.detach())._data)
            return out
        return op(x, gamma, beta, running_mean, running_var, eps=self._eps,
                  momentum=self._momentum, fix_gamma=not self._scale,
                  use_global_stats=True, output_mean_var=False,
                  axis=self._axis, **extra)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer, allow_deferred_init=True)
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape_inferred((c,))
        self.beta.shape_inferred((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer, allow_deferred_init=True)
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape_inferred((c,))
        self.beta.shape_inferred((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._ngroups = num_groups
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer, allow_deferred_init=True)
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape_inferred((c,))
        self.beta.shape_inferred((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._ngroups, eps=self._eps)
