"""Gluon conv/pool layers.

Reference: ``python/mxnet/gluon/nn/conv_layers.py`` (TBV — SURVEY.md §2.3).
Layouts follow the reference default (NCHW family); XLA relayouts for the MXU.
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
           "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D",
           "GlobalMaxPool3D", "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
           "ReflectionPad2D"]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation, groups,
                 layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", ndim=2,
                 transpose=False, output_padding=0, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = _tup(kernel_size, ndim)
        self._strides = _tup(strides, ndim)
        self._padding = _tup(padding, ndim)
        self._dilation = _tup(dilation, ndim)
        self._groups = groups
        self._act = activation
        self._transpose = transpose
        self._output_padding = _tup(output_padding, ndim)
        if transpose:
            wshape = (in_channels, channels // groups) + self._kernel
        else:
            wshape = (channels, in_channels // groups if in_channels else 0) + self._kernel
        self.weight = self.params.get("weight", shape=wshape, init=weight_initializer,
                                      allow_deferred_init=True)
        self.bias = self.params.get("bias", shape=(channels,),
                                    init=bias_initializer) if use_bias else None

    def infer_shape(self, x, *args):
        c_in = x.shape[1]
        if self._transpose:
            self.weight.shape_inferred((c_in, self._channels // self._groups) + self._kernel)
        else:
            self.weight.shape_inferred((self._channels, c_in // self._groups) + self._kernel)

    def hybrid_forward(self, F, x, weight, bias=None):
        if self._transpose:
            out = F.Deconvolution(x, weight, *([bias] if bias is not None else []),
                                  kernel=self._kernel, stride=self._strides,
                                  pad=self._padding, dilate=self._dilation,
                                  adj=self._output_padding, num_filter=self._channels,
                                  num_group=self._groups, no_bias=bias is None)
        else:
            out = F.Convolution(x, weight, *([bias] if bias is not None else []),
                                kernel=self._kernel, stride=self._strides,
                                pad=self._padding, dilate=self._dilation,
                                num_filter=self._channels, num_group=self._groups,
                                no_bias=bias is None)
        if self._act:
            out = F.Activation(out, act_type=self._act)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, ndim=1, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, ndim=2, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, ndim=3, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, output_padding=0,
                 dilation=1, groups=1, layout="NCW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, ndim=1, transpose=True, output_padding=output_padding,
                         **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, ndim=2, transpose=True, output_padding=output_padding,
                         **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 output_padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, ndim=3, transpose=True, output_padding=output_padding,
                         **kwargs)


class _Pool(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, pool_type, ndim,
                 global_pool=False, count_include_pad=True, **kwargs):
        super().__init__(**kwargs)
        self._kernel = _tup(pool_size, ndim)
        self._strides = _tup(strides if strides is not None else pool_size, ndim)
        self._padding = _tup(padding, ndim)
        self._ceil = ceil_mode
        self._ptype = pool_type
        self._global = global_pool
        self._cip = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, kernel=self._kernel, stride=self._strides,
                         pad=self._padding, pool_type=self._ptype,
                         global_pool=self._global,
                         pooling_convention="full" if self._ceil else "valid",
                         count_include_pad=self._cip)


class MaxPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, ceil_mode=False, **kw):
        super().__init__(pool_size, strides, padding, ceil_mode, "max", 1, **kw)


class MaxPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, ceil_mode=False, **kw):
        super().__init__(pool_size, strides, padding, ceil_mode, "max", 2, **kw)


class MaxPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, ceil_mode=False, **kw):
        super().__init__(pool_size, strides, padding, ceil_mode, "max", 3, **kw)


class AvgPool1D(_Pool):
    def __init__(self, pool_size=2, strides=None, padding=0, ceil_mode=False,
                 count_include_pad=True, **kw):
        super().__init__(pool_size, strides, padding, ceil_mode, "avg", 1,
                         count_include_pad=count_include_pad, **kw)


class AvgPool2D(_Pool):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, ceil_mode=False,
                 count_include_pad=True, **kw):
        super().__init__(pool_size, strides, padding, ceil_mode, "avg", 2,
                         count_include_pad=count_include_pad, **kw)


class AvgPool3D(_Pool):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, ceil_mode=False,
                 count_include_pad=True, **kw):
        super().__init__(pool_size, strides, padding, ceil_mode, "avg", 3,
                         count_include_pad=count_include_pad, **kw)


class GlobalMaxPool1D(_Pool):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, False, "max", 1, global_pool=True, **kw)


class GlobalMaxPool2D(_Pool):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, False, "max", 2, global_pool=True, **kw)


class GlobalMaxPool3D(_Pool):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, False, "max", 3, global_pool=True, **kw)


class GlobalAvgPool1D(_Pool):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, False, "avg", 1, global_pool=True, **kw)


class GlobalAvgPool2D(_Pool):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, False, "avg", 2, global_pool=True, **kw)


class GlobalAvgPool3D(_Pool):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, False, "avg", 3, global_pool=True, **kw)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        self._padding = _tup(padding, 2) if isinstance(padding, int) else tuple(padding)

    def hybrid_forward(self, F, x):
        p = self._padding
        pw = (0, 0, 0, 0, p[0], p[0], p[1], p[1]) if len(p) == 2 else p
        return F.Pad(x, mode="reflect", pad_width=pw)
