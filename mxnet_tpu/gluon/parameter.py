"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py`` (TBV — SURVEY.md §2.3): lazy
shape (deferred init), per-context copies, grad_req, constant params.

TPU redesign: a Parameter holds ONE logical NDArray. The reference keeps an
explicit copy per GPU and all-reduces grads across them; here multi-device
data-parallel is expressed with jax.sharding on the single logical array
(replicated or sharded over the Mesh), so `list_ctx` is a compatibility veneer.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import numpy as np

from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from ..ndarray import NDArray, zeros
from .. import initializer as _initializer

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before shape was inferred + initialized."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype_np(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._data: Optional[NDArray] = None
        self._deferred_init = None  # (init, ctx) captured at initialize()
        self._sharding = None  # jax.sharding spec set by parallel layer
        self._obsolete = False

    # ------------------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            self._data.attach_grad(req) if req != "null" else None

    def _shape_known(self) -> bool:
        return self.shape is not None and all(s > 0 for s in self.shape)

    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0] if ctx else None  # single logical array (see module doc)
        ctx = ctx or current_context()
        eff_init = _initializer.create(self.init if init is None else init) \
            if (self.init is not None or init is not None) \
            else _initializer.create(default_init or "uniform")
        if not self._shape_known():
            if not self.allow_deferred_init:
                raise ValueError(
                    f"Cannot initialize Parameter {self.name!r}: shape {self.shape} "
                    f"unknown and deferred init not allowed")
            self._deferred_init = (eff_init, ctx)
            return
        self._finish_init(eff_init, ctx)

    def _finish_init(self, init, ctx):
        arr = zeros(self.shape, dtype=self.dtype, ctx=ctx)
        init(self.name, arr)
        self._data = arr
        self._deferred_init = None
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req)

    def _finish_deferred_init(self, inferred_shape):
        if self._deferred_init is None:
            raise DeferredInitializationError(
                f"Parameter {self.name!r} has unknown shape and was not initialize()d")
        self.shape = tuple(int(s) for s in inferred_shape)
        init, ctx = self._deferred_init
        self._finish_init(init, ctx)

    def shape_inferred(self, shape):
        """Called by the owning layer at first forward with the actual shape."""
        if self._data is None:
            if self.shape is not None and len(self.shape) == len(shape):
                merged = tuple(int(b) if s in (0, -1, None) else int(s)
                               for s, b in zip(self.shape, shape))
            else:
                merged = tuple(int(s) for s in shape)
            self._finish_deferred_init(merged)

    # ------------------------------------------------------------------
    def data(self, ctx=None) -> NDArray:
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name!r} deferred-initialized; run a forward "
                    f"pass (or set shape) before accessing data()")
            raise RuntimeError(f"Parameter {self.name!r} has not been initialized")
        return self._data

    def list_data(self):
        return [self.data()]

    def list_ctx(self):
        return [self.data().context]

    @property
    def grad_(self):
        return self.data().grad

    def grad(self, ctx=None) -> NDArray:
        g = self.data().grad
        if g is None:
            raise RuntimeError(f"Parameter {self.name!r} has no gradient "
                               f"(grad_req={self._grad_req!r})")
        return g

    def list_grad(self):
        return [self.grad()]

    def zero_grad(self):
        g = self.data().grad
        if g is not None:
            g[:] = 0

    def set_data(self, data):
        if self._data is None:
            if isinstance(data, NDArray):
                self.shape = data.shape
                self._data = data.copy()
                if self._grad_req != "null":
                    self._data.attach_grad(self._grad_req)
            return
        self._data._set_data(data._data if isinstance(data, NDArray) else data)

    def cast(self, dtype):
        self.dtype = dtype_np(dtype)
        if self._data is not None:
            req = self._grad_req
            self._data = self._data.astype(self.dtype)
            if req != "null":
                self._data.attach_grad(req)

    def reset_ctx(self, ctx):
        if self._data is not None:
            req = self._grad_req
            self._data = self._data.as_in_context(ctx if not isinstance(ctx, (list, tuple)) else ctx[0])
            if req != "null":
                self._data.attach_grad(req)

    def var(self):
        from ..symbol import Symbol

        return Symbol.var(self.name, shape=self.shape)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={np.dtype(self.dtype).name})"


class Constant(Parameter):
    """Non-trainable parameter holding a fixed value."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            from ..ndarray import array

            value = array(value)
        super().__init__(name, grad_req="null", shape=value.shape, dtype=value.dtype,
                         init="zeros")
        self._value = value

    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        if self._data is None or force_reinit:
            self._data = self._value.copy()


class ParameterDict:
    """Ordered name → Parameter mapping with a shared prefix.

    Reference gluon.ParameterDict; also the unit the KVStore keys off.
    """

    def __init__(self, prefix="", shared=None):
        self.prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def get(self, name, **kwargs) -> Parameter:
        full = self.prefix + name
        if full in self._params:
            return self._params[full]
        if self._shared is not None and full in self._shared._params:
            self._params[full] = self._shared._params[full]
            return self._params[full]
        p = Parameter(full, **kwargs)
        self._params[full] = p
        return p

    def get_constant(self, name, value=None) -> Constant:
        full = self.prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        for p in self.values():
            p.initialize(init=None, ctx=ctx, default_init=init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import save as nd_save

        arg = {}
        for p in self.values():
            n = p.name
            if strip_prefix and n.startswith(strip_prefix):
                n = n[len(strip_prefix):]
            arg[n] = p.data()
        nd_save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False, ignore_extra=False,
             restore_prefix=""):
        from ..ndarray import load as nd_load

        loaded = nd_load(filename)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self._params.items():
            if name in loaded:
                if p._data is None:
                    p.shape = loaded[name].shape
                    p.initialize()
                p.set_data(loaded[name])
            elif not allow_missing:
                raise KeyError(f"Parameter {name} missing from file {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise KeyError(f"file {filename} has extra parameters: {sorted(extra)}")

    # dict protocol ----------------------------------------------------
    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __getitem__(self, k):
        return self._params[k]

    def __contains__(self, k):
        return k in self._params

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __repr__(self):
        items = "\n".join(f"  {p!r}" for p in self.values())
        return f"ParameterDict(prefix={self.prefix!r}\n{items}\n)"
