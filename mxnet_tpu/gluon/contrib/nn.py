"""gluon.contrib.nn — SyncBatchNorm and friends (reference
python/mxnet/gluon/contrib/nn/basic_layers.py — TBV).
"""
from __future__ import annotations

from ..block import HybridBlock
from ..nn.basic_layers import BatchNorm

__all__ = ["SyncBatchNorm"]


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm: batch moments are reduced over the
    data-parallel mesh axis (the reference reduces over ``num_devices`` GPUs
    via its cross-GPU key-value AllReduce; here the reduction is a
    ``lax.pmean`` inserted when the layer is traced inside the sharded train
    step — see ops/contrib.py _contrib_SyncBatchNorm).

    ``num_devices`` is accepted for API compat but unused: the mesh in scope
    at trace time defines the reduction group.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", axis_name="dp", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices
        self._axis_name = axis_name

    def _bn_op(self, F):
        return F.SyncBatchNorm, {"axis_name": self._axis_name}


class Identity(HybridBlock):
    """Passthrough block (reference gluon.contrib.nn.Identity)."""

    def hybrid_forward(self, F, x):
        return x


class HybridConcurrent(HybridBlock):
    """Feed one input to every child and concat their outputs on ``axis``
    (reference gluon.contrib.nn.HybridConcurrent — the Inception-branch
    combinator)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_forward(self, F, x):
        outs = [b(x) for b in self._children.values()]
        return F.concat(*outs, dim=self._axis)


class Concurrent(HybridConcurrent):
    """Imperative alias of HybridConcurrent (reference keeps both)."""
