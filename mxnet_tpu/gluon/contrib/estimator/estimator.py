"""Estimator — the high-level Gluon fit loop.

Reference: ``gluon/contrib/estimator/estimator.py`` (expected path per
SURVEY.md §2.3; mount empty this round): wraps net + loss + metrics +
Trainer, drives epochs/batches, and dispatches the event-handler lifecycle
(train_begin → [epoch_begin → [batch_begin → batch_end]* → epoch_end]* →
train_end). TPU notes: the train step is autograd.record + backward +
Trainer.step — the same imperative path Gluon users write by hand; swap in
parallel.ShardedTrainer manually for mesh-scale runs.
"""
from __future__ import annotations

import copy
import logging

from .... import autograd
from ....metric import EvalMetric, Loss as LossMetric
from ....ndarray import NDArray
from ...trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, StoppingHandler,
                            TrainBegin, TrainEnd, ValidationHandler)

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None):
        self.net = net
        self.loss = loss
        self.train_metrics = _as_metrics(metrics)
        # one Loss metric tracking the objective, like the reference
        if not any(isinstance(m, LossMetric) for m in self.train_metrics):
            self.train_metrics.append(LossMetric("loss"))
        self.val_metrics = [copy.deepcopy(m) for m in self.train_metrics]
        for m in self.val_metrics:
            m.name = "validation " + m.name
        self.context = context
        if initializer is not None:
            self.net.initialize(initializer)
        self.trainer = trainer or Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.001})
        self.logger = logging.getLogger("mxnet_tpu.estimator")
        self.stop_training = False

    # ------------------------------------------------------------------
    def evaluate(self, val_data, batch_axis=0):
        for m in self.val_metrics:
            m.reset()
        for batch in val_data:
            data, label = _split_batch(batch)
            pred = self.net(data)
            loss = self.loss(pred, label)
            for m in self.val_metrics:
                if isinstance(m, LossMetric):
                    m.update(0, loss)
                else:
                    m.update(label, pred)
        return self.val_metrics

    # ------------------------------------------------------------------
    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        if epochs is None and batches is None:
            epochs = 1
        handlers = self._prepare_handlers(event_handlers, val_data, epochs,
                                          batches)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = _sort_phases(handlers)

        self.stop_training = False
        for h in train_begin:
            h.train_begin(self)
        # train_end handlers hold cleanup that must not leak past an
        # aborted fit (HealthHandler's plane deactivation, checkpoint
        # flushes): on an exception they still run, errors swallowed so
        # cleanup can't mask the real failure
        unwinding = True
        try:
            while not self.stop_training:
                for h in epoch_begin:
                    h.epoch_begin(self)
                for batch in train_data:
                    data, label = _split_batch(batch)
                    for h in batch_begin:
                        h.batch_begin(self, batch=batch)
                    with autograd.record():
                        pred = self.net(data)
                        loss = self.loss(pred, label)
                    loss.backward()
                    self.trainer.step(_batch_size(data, batch_axis))
                    for h in batch_end:
                        if h.batch_end(self, batch=batch, pred=pred,
                                       label=label, loss=loss):
                            self.stop_training = True
                    if self.stop_training:
                        break
                for h in epoch_end:
                    if h.epoch_end(self):
                        self.stop_training = True
                if hasattr(train_data, "reset"):
                    train_data.reset()
            unwinding = False
        finally:
            for h in train_end:
                try:
                    h.train_end(self)
                except Exception:
                    if not unwinding:
                        raise
                    self.logger.warning("train_end handler failed during "
                                        "unwind", exc_info=True)
        return self.train_metrics

    # ------------------------------------------------------------------
    def _prepare_handlers(self, event_handlers, val_data, epochs, batches):
        handlers = list(event_handlers or [])
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(max_epoch=epochs,
                                            max_batch=batches))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(metrics=self.train_metrics))
        return handlers


def _as_metrics(metrics):
    if metrics is None:
        return []
    metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
    for m in metrics:
        if not isinstance(m, EvalMetric):
            raise ValueError(f"metrics must be EvalMetric, got {type(m)}")
    return list(metrics)


def _split_batch(batch):
    if hasattr(batch, "data"):  # DataBatch from an io iterator
        return batch.data[0], batch.label[0]
    data, label = batch[0], batch[1]
    return data, label


def _batch_size(data, batch_axis):
    return data.shape[batch_axis]


def _sort_phases(handlers):
    def by_priority(hs):
        return sorted(hs, key=lambda h: getattr(h, "priority", 0))

    return (by_priority([h for h in handlers if isinstance(h, TrainBegin)]),
            by_priority([h for h in handlers if isinstance(h, EpochBegin)]),
            by_priority([h for h in handlers if isinstance(h, BatchBegin)]),
            by_priority([h for h in handlers if isinstance(h, BatchEnd)]),
            by_priority([h for h in handlers if isinstance(h, EpochEnd)]),
            by_priority([h for h in handlers if isinstance(h, TrainEnd)]))
