"""Estimator API (reference gluon/contrib/estimator/ — SURVEY.md §2.3)."""
from .estimator import Estimator  # noqa: F401
from .event_handler import (BatchBegin, BatchEnd, CheckpointHandler,  # noqa: F401
                            EarlyStoppingHandler, EpochBegin, EpochEnd,
                            EventHandler, HealthHandler, LoggingHandler,
                            MetricHandler, StoppingHandler, TrainBegin,
                            TrainEnd, ValidationHandler)
