"""Estimator event handlers (reference
``gluon/contrib/estimator/event_handler.py`` — expected path per SURVEY.md
§2.3; mount empty this round). Same mixin contract: a handler subclasses any
of the six phase mixins and the Estimator calls it at that phase."""
from __future__ import annotations

import logging
import os
import time

import numpy as np

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "EventHandler", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler", "HealthHandler"]


class EventHandler:
    pass


class TrainBegin(EventHandler):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(EventHandler):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(EventHandler):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(EventHandler):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(EventHandler):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(EventHandler):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch / max_batch (reference StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    """Reset metrics per epoch, update per batch (reference MetricHandler)."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            if _is_loss_metric(m):
                m.update(0, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation every N epochs/batches (reference ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Log throughput + metric values (reference LoggingHandler)."""

    LOG_PER_EPOCH = 1
    LOG_PER_BATCH = 2

    def __init__(self, log_interval="epoch", metrics=None, priority=float("inf")):
        self.metrics = metrics or []
        self.log_interval = log_interval
        self.priority = priority
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        t = time.time() - self.train_start
        self.logger.info("Train finished using total %ds", t)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0
        self.processed_samples = 0

    def epoch_end(self, estimator, *args, **kwargs):
        t = time.time() - self.epoch_start
        msg = f"Epoch {self.current_epoch} finished in {t:.3f}s: "
        for m in self.metrics:
            name, value = m.get()
            msg += f"{name}: {_fmt(value)} "
        self.logger.info(msg)
        self.current_epoch += 1

    def batch_begin(self, estimator, *args, **kwargs):
        if self.log_interval == "batch" or isinstance(self.log_interval, int):
            self.batch_start = time.time()

    def batch_end(self, estimator, *args, **kwargs):
        interval = self.log_interval
        batch = kwargs.get("batch")
        if batch is not None and hasattr(batch, "data"):
            self.processed_samples += batch.data[0].shape[0]
        self.batch_index += 1
        if interval == "epoch":
            return
        every = 1 if interval == "batch" else int(interval)
        if self.batch_index % every == 0:
            t = time.time() - self.batch_start
            msg = f"[Epoch {self.current_epoch}][Batch {self.batch_index}]"
            msg += f"[Samples {self.processed_samples}] time/batch: {t:.3f}s "
            for m in self.metrics:
                name, value = m.get()
                msg += f"{name}: {_fmt(value)} "
            self.logger.info(msg)


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd, TrainEnd):
    """Save params (+trainer states) per epoch; keep the best by monitor.

    Two layers (docs/ROBUSTNESS.md): the legacy per-tag ``.params`` /
    ``.states`` files (now written atomically), and a full-training-state
    :class:`~mxnet_tpu.checkpoint.CheckpointManager` under the same
    directory — atomic rename commits, per-array CRC32, keep-last-N GC.
    ``resume_from_checkpoint=True`` restores the newest *valid* full-state
    checkpoint (net params + optimizer slots and counters + RNG streams) at
    ``train_begin``; corrupt checkpoints are skipped.
    """

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5, resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.resume_from_checkpoint = resume_from_checkpoint
        self.current_epoch = 0
        self.current_batch = 0
        self.saved = []
        self.resumed_from = None
        if mode == "auto" and monitor is not None:
            name = monitor.get()[0]
            mode = "max" if "acc" in name or "f1" in name else "min"
        self._cmp = (np.greater if mode == "max" else np.less)
        self.best = -np.inf if mode == "max" else np.inf
        os.makedirs(model_dir, exist_ok=True)
        from ....checkpoint import CheckpointManager

        self._manager = CheckpointManager(model_dir, prefix=model_prefix,
                                          keep_last=max_checkpoints)

    def train_begin(self, estimator, *args, **kwargs):
        if not self.resume_from_checkpoint:
            return
        from ....checkpoint.state import restore_rng
        from ....ndarray import NDArray

        state = self._manager.load_latest()
        if state is None:
            return
        # structural names (as save_parameters uses), NOT p.name: the gluon
        # auto-prefix counter differs in a fresh process, so dense0_weight
        # would never match the restarted net's dense1_weight
        params = estimator.net._collect_params_with_prefix()
        for name, arr in state.arg_params().items():
            if name in params:
                p = params[name]
                if p._data is None:
                    p.shape = arr.shape
                    p.initialize()
                p.set_data(NDArray(arr))
        if estimator.trainer is not None:
            estimator.trainer.set_checkpoint_state(
                {"arrays": state.arrays, "optimizer":
                 state.meta.get("optimizer", {})})
        restore_rng(state)
        self.current_epoch = state.meta.get("epochs_done", 0)
        self.current_batch = state.meta.get("batches_done", 0)
        self.resumed_from = state.global_step
        logging.info("CheckpointHandler: resumed from step %d "
                     "(%d epochs done)", state.global_step, self.current_epoch)

    def _save(self, estimator, tag):
        path = os.path.join(self.model_dir, f"{self.model_prefix}-{tag}.params")
        estimator.net.save_parameters(path)
        self.saved.append(path)
        if estimator.trainer is not None:
            try:
                estimator.trainer.save_states(path.replace(".params", ".states"))
            except Exception:
                pass

    def _save_full(self, estimator):
        from ....checkpoint.state import capture_training_state

        trainer = estimator.trainer
        updater = trainer._updaters[0] if trainer is not None else None
        optimizer = trainer._optimizer if trainer is not None else None
        state = capture_training_state(
            arg_params={name: p.data() for name, p in
                        estimator.net._collect_params_with_prefix().items()
                        if p._data is not None},
            updater=updater, optimizer=optimizer,
            global_step=self.current_batch,
            extra_meta={"epochs_done": self.current_epoch,
                        "batches_done": self.current_batch})
        self._manager.save(state, self.current_batch)

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator, f"batch{self.current_batch}")
            self._save_full(estimator)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator, f"epoch{self.current_epoch - 1}")
            self._save_full(estimator)
        if self.save_best and self.monitor is not None:
            value = self.monitor.get()[1]
            if np.isscalar(value) and self._cmp(value, self.best):
                self.best = value
                self._save(estimator, "best")

    def train_end(self, estimator, *args, **kwargs):
        self._manager.flush()  # drain the async writer before exit


class HealthHandler(TrainBegin, BatchBegin, BatchEnd, TrainEnd):
    """Wire the training-health sentinel (docs/OBSERVABILITY.md "Training
    health") into an Estimator fit: feeds the per-batch loss (a device
    reference — synced only at sampled steps), drives the monitor off the
    trainer's fused engine + AMP scaler, and lets ``actions="lr_backoff"``
    apply in place. ``stop_on_nonfinite=True`` additionally halts the fit
    on a non-finite breach (an estimator has no checkpoint-rollback loop
    of its own — stopping honestly beats training on NaN).
    """

    def __init__(self, monitor=True, stop_on_nonfinite=False, priority=-500):
        from ....obs import health as health_mod

        self.monitor = health_mod.as_monitor(monitor)
        if self.monitor is None:
            # a health handler with monitoring opted out is a contradiction
            # — reject loudly instead of silently monitoring anyway
            raise ValueError("HealthHandler needs a monitor: pass True, a "
                             "kwargs dict, or a HealthMonitor (to disable "
                             "health monitoring, don't add the handler)")
        self.stop_on_nonfinite = stop_on_nonfinite
        self.priority = priority
        self._active = False

    def train_begin(self, estimator, *args, **kwargs):
        from ....obs import health as health_mod

        if not self._active:
            health_mod.activate()
            self._active = True
        trainer = estimator.trainer
        if trainer is not None and self.monitor.param_names is None:
            self.monitor.attach_names([p.name for p in trainer._params])

    def batch_begin(self, estimator, *args, **kwargs):
        from ....obs import health as health_mod

        # this batch's trainer.step runs before batch_end: emit the stats
        # variant exactly when the sentinel will sample it
        health_mod.request_stats(self.monitor.will_sample())

    def batch_end(self, estimator, *args, **kwargs):
        trainer = estimator.trainer
        self.monitor.record_loss(kwargs.get("loss"))
        rep = self.monitor.step(
            engine=getattr(trainer._updaters[0], "_engine", None)
            if trainer is not None else None,
            scaler=getattr(trainer, "_amp_loss_scaler", None)
            if trainer is not None else None,
            optimizer=trainer._optimizer if trainer is not None else None)
        if (self.stop_on_nonfinite and rep is not None
                and any(b["rule"] == "nonfinite"
                        for b in rep.get("breaches", ()))):
            logging.getLogger("mxnet_tpu.estimator").error(
                "HealthHandler: non-finite breach — stopping training")
            return True
        return False

    def train_end(self, estimator, *args, **kwargs):
        from ....obs import health as health_mod

        if self._active:
            health_mod.request_stats(None)
            health_mod.deactivate()
            self._active = False


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when the monitored metric stops improving (reference analog)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        name = monitor.get()[0]
        if mode == "auto":
            mode = "max" if "acc" in name or "f1" in name else "min"
        self._mode = mode

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stop_training = False
        self.best = -np.inf if self._mode == "max" else np.inf
        if self.baseline is not None:
            self.best = self.baseline

    def _improved(self, value):
        if self._mode == "max":
            return value > self.best + self.min_delta
        return value < self.best - self.min_delta

    def epoch_end(self, estimator, *args, **kwargs):
        value = self.monitor.get()[1]
        if not np.isscalar(value):
            return self.stop_training
        if self._improved(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                self.stop_training = True
        self.current_epoch += 1
        return self.stop_training

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch > 0:
            logging.getLogger("mxnet_tpu.estimator").info(
                "Epoch %d: early stopping", self.stopped_epoch)


def _is_loss_metric(m):
    from ....metric import Loss

    return isinstance(m, Loss)


def _fmt(v):
    return f"{v:.4f}" if np.isscalar(v) and np.isfinite(v) else str(v)
