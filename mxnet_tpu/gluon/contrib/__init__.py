"""gluon.contrib — experimental Gluon surface (reference
python/mxnet/gluon/contrib/, expected path per SURVEY.md §2.3)."""
from . import estimator  # noqa: F401
from . import nn  # noqa: F401
