"""Fused recurrent layers: RNN / LSTM / GRU.

Reference: ``python/mxnet/gluon/rnn/rnn_layer.py`` (``_RNNLayer`` packing
per-layer i2h/h2h parameters into the flat vector the fused ``RNN`` op
consumes — TBV, SURVEY.md §2.3). Parameters are held unfused (one
``{lN}_{dir}_i2h_weight`` etc. per layer/direction, matching reference
checkpoint naming) and concatenated at forward time; under hybridize the
concat is traced once and fuses into the scan's GEMMs.
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers=1, layout="TNC", dropout=0.0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", dtype="float32", **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), f"invalid layout {layout!r}"
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._dtype = dtype
        self._gates = _GATES[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][: self._dir]:
                self._register_param(f"{j}{i}_i2h_weight", (ng * nh, ni),
                                     i2h_weight_initializer, dtype)
                self._register_param(f"{j}{i}_h2h_weight", (ng * nh, nh),
                                     h2h_weight_initializer, dtype)
                self._register_param(f"{j}{i}_i2h_bias", (ng * nh,),
                                     i2h_bias_initializer, dtype)
                self._register_param(f"{j}{i}_h2h_bias", (ng * nh,),
                                     h2h_bias_initializer, dtype)
            ni = nh * self._dir

    def _register_param(self, name, shape, init, dtype):
        p = self.params.get(name, shape=shape, init=init, dtype=dtype,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def infer_shape(self, x, *args):
        ni = x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                getattr(self, f"{j}{i}_i2h_weight").shape_inferred((ng * nh, ni))
            ni = nh * self._dir
        self._input_size = self._input_size or x.shape[-1]

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, ctx=None, dtype=None, **kwargs):
        from ... import ndarray as nd

        func = func or nd.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], ctx=ctx,
                               dtype=dtype or self._dtype, **kwargs))
        return states

    def _ordered_params(self):
        """Weights for every layer/direction first, then biases — the packed
        layout ops.rnn.rnn_unpack_params expects."""
        ps = []
        for kind in ("weight", "bias"):
            for i in range(self._num_layers):
                for j in ["l", "r"][: self._dir]:
                    ps.append(getattr(self, f"{j}{i}_i2h_{kind}"))
                    ps.append(getattr(self, f"{j}{i}_h2h_{kind}"))
        return ps

    def hybrid_forward(self, F, x, states=None, **params):
        # params (captured via _reg_params) arrive as kwargs name -> NDArray.
        if isinstance(states, (list, tuple)) and len(states) == 0:
            states = None
        if self._layout == "NTC":
            x = F.swapaxes(x, dim1=0, dim2=1)
        batch = x.shape[1]
        skip_states = states is None
        if skip_states:
            # Trace-safe zero states (x may be a tracer under hybridize, so no
            # device query here; F.zeros lands on the default device).
            states = [F.zeros(shape=info["shape"], dtype=str(x.dtype))
                      for info in self.state_info(batch)]
        if not isinstance(states, (list, tuple)):
            states = [states]
        flat = F.concat(*[params[n].reshape(-1) for n in self._param_order_names()],
                        dim=0)
        out = F.RNN(x, flat, *states, state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True)
        outputs, rstates = out[0], list(out[1:])
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if skip_states:
            return outputs
        return outputs, rstates

    def _param_order_names(self):
        names = []
        for kind in ("weight", "bias"):
            for i in range(self._num_layers):
                for j in ["l", "r"][: self._dir]:
                    names.append(f"{j}{i}_i2h_{kind}")
                    names.append(f"{j}{i}_h2h_{kind}")
        return names

    def __repr__(self):
        return (f"{self.__class__.__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, layout='{self._layout}', "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    """Vanilla Elman RNN with tanh or relu (reference gluon.rnn.RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu", layout="TNC",
                 dropout=0, bidirectional=False, input_size=0, **kwargs):
        super().__init__("rnn_" + activation, hidden_size, num_layers, layout,
                         dropout, bidirectional, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size),
                 "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference gluon.rnn.LSTM over the fused RNN op)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU, cuDNN gate convention (reference gluon.rnn.GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size),
                 "__layout__": "LNC"}]
