"""Recurrent cells + unroll — the step-at-a-time API.

Reference: ``python/mxnet/gluon/rnn/rnn_cell.py`` (TBV — SURVEY.md §2.3).
Cells are ordinary HybridBlocks computing one timestep; ``unroll`` runs a
Python loop over a static length, which under hybridize traces to a fully
unrolled XLA program (fine for short decoding loops; the fused
``rnn_layer``/``lax.scan`` path is the long-sequence fast path).
"""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "BidirectionalCell", "DropoutCell", "ResidualCell",
           "ZoneoutCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _format_sequence(length, inputs, layout, merge):
    """Split (T,N,C)/(N,T,C) NDArray into a list of (N,C) steps, or re-merge."""
    from ... import ndarray as F

    axis = layout.find("T")
    if isinstance(inputs, (list, tuple)):
        if merge:
            stacked = F.stack(*inputs, axis=axis)
            return stacked, axis
        return list(inputs), axis
    length = length or inputs.shape[axis]
    if merge is False:
        steps = F.split(inputs, num_outputs=length, axis=axis, squeeze_axis=True)
        if length == 1:
            steps = [steps]
        return list(steps), axis
    return inputs, axis


class RecurrentCell(HybridBlock):
    """Base cell: one step of recurrence + unroll()."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False
        self._init_counter = -1

    def reset(self):
        self._init_counter = -1

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, ctx=None, dtype="float32", **kwargs):
        from ... import ndarray as nd

        func = func or nd.zeros
        return [func(shape=info["shape"], ctx=ctx, dtype=dtype, **kwargs)
                for info in self.state_info(batch_size)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F

        steps, axis = _format_sequence(length, inputs, layout, False)
        batch = steps[0].shape[0]
        states = begin_state if begin_state is not None else self.begin_state(
            batch, dtype=str(steps[0].dtype))
        outputs = []
        for t in range(length):
            out, states = self(steps[t], states)
            outputs.append(out)
        if valid_length is not None:
            stacked = F.stack(*outputs, axis=0)  # (T, N, C)
            masked = F.SequenceMask(stacked, sequence_length=valid_length,
                                    use_sequence_length=True, axis=0)
            outputs = F.split(masked, num_outputs=length, axis=0, squeeze_axis=True)
            outputs = [outputs] if length == 1 else list(outputs)
        if merge_outputs:
            outputs, _ = _format_sequence(length, outputs, layout, True)
        return outputs, states


class HybridRecurrentCell(RecurrentCell):
    pass


class RNNCell(HybridRecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self.i2h_weight = self.params.get("i2h_weight", shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                        init=i2h_bias_initializer)
        self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                        init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_inferred((self._hidden_size, x.shape[-1]))
        self.h2h_weight.shape_inferred((self._hidden_size, self._hidden_size))

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(HybridRecurrentCell):
    """One LSTM step; gate order [i, f, g, o] matches the fused RNN op."""

    def __init__(self, hidden_size, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self.i2h_weight = self.params.get("i2h_weight", shape=(4 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(4 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                        init=i2h_bias_initializer)
        self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                        init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_inferred((4 * self._hidden_size, x.shape[-1]))
        self.h2h_weight.shape_inferred((4 * self._hidden_size, self._hidden_size))

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        nh = self._hidden_size
        gates = (F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=4 * nh)
                 + F.FullyConnected(states[0], h2h_weight, h2h_bias, num_hidden=4 * nh))
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        c = F.sigmoid(f) * states[1] + F.sigmoid(i) * F.tanh(g)
        h = F.sigmoid(o) * F.tanh(c)
        return h, [h, c]


class GRUCell(HybridRecurrentCell):
    """One GRU step; gate order [r, z, n], cuDNN linear-before-reset variant."""

    def __init__(self, hidden_size, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self.i2h_weight = self.params.get("i2h_weight", shape=(3 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight", shape=(3 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                        init=i2h_bias_initializer)
        self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                        init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_inferred((3 * self._hidden_size, x.shape[-1]))
        self.h2h_weight.shape_inferred((3 * self._hidden_size, self._hidden_size))

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        nh = self._hidden_size
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=3 * nh)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias, num_hidden=3 * nh)
        ir, iz, inn = F.split(i2h, num_outputs=3, axis=-1)
        hr, hz, hn = F.split(h2h, num_outputs=3, axis=-1)
        r = F.sigmoid(ir + hr)
        z = F.sigmoid(iz + hz)
        n = F.tanh(inn + r * hn)
        h = (1.0 - z) * n + z * states[0]
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells vertically (reference SequentialRNNCell)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def __call__(self, x, states):
        return self.forward(x, states)

    def forward(self, x, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            x, cell_states = cell(x, states[p:p + n])
            next_states.extend(cell_states)
            p += n
        return x, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        batch = (inputs[0] if isinstance(inputs, (list, tuple)) else inputs).shape[
            0 if layout[0] == "N" else 1]
        states = begin_state if begin_state is not None else self.begin_state(batch)
        p = 0
        next_states = []
        cells = list(self._children.values())
        for i, cell in enumerate(cells):
            n = len(cell.state_info())
            inputs, cell_states = cell.unroll(
                length, inputs, states[p:p + n], layout,
                merge_outputs=None if i < len(cells) - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(cell_states)
            p += n
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, x, states):
        if self._rate:
            x = F.Dropout(x, p=self._rate)
        return x, states


class _ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)


class ResidualCell(_ModifierCell):
    def hybrid_forward(self, F, x, states):
        out, states = self.base_cell(x, states)
        return out + x, states


class ZoneoutCell(_ModifierCell):
    """Zoneout regularization: randomly keep previous states."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0, **kwargs):
        super().__init__(base_cell, **kwargs)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, x, states):
        from ... import autograd

        out, next_states = self.base_cell(x, states)
        if autograd.is_training():
            def mask(p, like):
                return F.Dropout(F.ones_like(like), p=p)

            if self._zo:
                prev = self._prev_output if self._prev_output is not None else F.zeros_like(out)
                m = mask(self._zo, out)
                out = F.where(m, out, prev)
            if self._zs:
                next_states = [F.where(mask(self._zs, ns), ns, s)
                               for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class BidirectionalCell(HybridRecurrentCell):
    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return _cells_state_info([self.l_cell, self.r_cell], batch_size)

    def __call__(self, x, states):
        raise NotImplementedError("BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F

        steps, axis = _format_sequence(length, inputs, layout, False)
        batch = steps[0].shape[0]
        states = begin_state if begin_state is not None else self.begin_state(
            batch, dtype=str(steps[0].dtype))
        def _seq_reverse(step_list):
            """Reverse a list of (N,C) steps along time; sequence-length-aware
            when valid_length is given (padding stays in place, like the
            reference's SequenceReverse-based masking)."""
            if valid_length is None:
                return list(reversed(step_list))
            revd = F.SequenceReverse(F.stack(*step_list, axis=0),
                                     sequence_length=valid_length,
                                     use_sequence_length=True, axis=0)
            parts = F.split(revd, num_outputs=length, axis=0, squeeze_axis=True)
            return [parts] if length == 1 else list(parts)

        nl = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(length, steps, states[:nl], layout="NTC",
                                             merge_outputs=False,
                                             valid_length=valid_length)
        r_out, r_states = self.r_cell.unroll(length, _seq_reverse(steps), states[nl:],
                                             layout="NTC", merge_outputs=False,
                                             valid_length=valid_length)
        r_out = _seq_reverse(r_out)
        outputs = [F.concat(l, r, dim=-1) for l, r in zip(l_out, r_out)]
        if merge_outputs:
            outputs, _ = _format_sequence(length, outputs, layout, True)
        return outputs, l_states + r_states
