"""Optimizer package (reference python/mxnet/optimizer/)."""
from .optimizer import (Optimizer, SGD, NAG, Adam, AdamW, LAMB, RMSProp, AdaGrad,
                        AdaDelta, Ftrl, FTML, Signum, AdaMax, Adamax, Nadam,
                        SGLD, DCASGD, LARS, create, register, Updater,
                        get_updater)
from . import lr_scheduler  # noqa: F401
from . import fused  # noqa: F401  (the one-program-per-step update engine)
from .fused import FusedUpdateEngine, fused_update_enabled

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "LAMB", "RMSProp", "AdaGrad",
           "AdaDelta", "Ftrl", "FTML", "Signum", "AdaMax", "Adamax", "Nadam",
           "SGLD", "DCASGD", "LARS", "create", "register", "Updater",
           "get_updater", "lr_scheduler", "fused", "FusedUpdateEngine",
           "fused_update_enabled"]
