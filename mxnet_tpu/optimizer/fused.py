"""Fused update engine — ONE donated XLA program per optimizer step.

The reference MXNet amortizes per-op dispatch with its dependency engine and
hand-fused multi-tensor kernels (``multi_sgd_update`` etc.).  Our TPU mapping
replaces the engine with XLA, but the eager update paths (gluon ``Trainer``,
``Module``'s updater, kvstore local updates) still ran one dispatch per
parameter per step — hundreds of tiny device programs for a ResNet.  This
module lowers every registered optimizer to a pure tree-level transform

    (params, grads, states, lrs, wds, ts, ...) -> (params', states')

compiled as one ``jax.jit`` program with (on accelerators) donated
param/state buffers, and with the cross-parameter work fused in:

- **global-norm gradient clipping** — the concat-norm and the scale are
  computed in-graph, no host round-trip;
- **AMP loss-scaler unscale + nonfinite-skip** — gradients are unscaled,
  the found-inf reduction is computed over all gradients, and the whole
  update is masked with ``where`` on the device flag.  The loss-scale /
  unskipped-step counters also advance in-graph, so a skip step costs zero
  host syncs;
- **LAMB/LARS trust ratios** — per-tensor norms stay in the program.

Per-step hyperparameters (lr after scheduler + multipliers, wd, update
counts, rescale_grad, loss scale) are **traced arguments**, so a scheduler
stepping the lr every iteration does not retrace.  Static hyperparameters
(betas, momentum, clip_gradient, ...) are baked into the program and keyed
into the compile cache; mutating them mid-run recompiles (the TraceLinter's
``update-retrace-churn`` rule flags pathological churn).

The per-parameter eager path is kept behind ``MXNET_FUSED_UPDATE=0`` as the
differential-testing oracle (tests/test_fused_update.py).  Buffer donation
follows ``MXNET_FUSED_DONATE`` (default: on for non-CPU backends — the CPU
PJRT client does not implement donation).  See docs/PERFORMANCE.md.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..ndarray import NDArray
from ..ops import get_op

__all__ = ["FusedUpdateEngine", "fused_update_enabled", "lower_update",
           "supports"]


def fused_update_enabled() -> bool:
    """The ``MXNET_FUSED_UPDATE`` escape hatch, read per call so tests can
    flip between the engine and the eager oracle without reimporting."""
    return os.environ.get("MXNET_FUSED_UPDATE", "1").lower() not in (
        "0", "false", "no", "off")


def _donate_default() -> bool:
    env = os.environ.get("MXNET_FUSED_DONATE")
    if env is not None:
        return env.lower() not in ("0", "false", "no", "off")
    # CPU PJRT has no donation support — jax would warn per compile
    return jax.default_backend() != "cpu"


def _f(name):
    return get_op(name).fn


# ---------------------------------------------------------------------------
# optimizer-state tree helpers.  Updater slots are nested tuples of NDArrays
# (or None); the engine flattens them to jax leaves and rebuilds in-trace.
# ---------------------------------------------------------------------------

def _state_spec(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_state_spec(x) for x in s)
    return "leaf"


def _state_leaves(s, out: list):
    if s is None:
        return
    if isinstance(s, tuple):
        for x in s:
            _state_leaves(x, out)
    else:
        out.append(s)


def _rebuild_state(spec, it):
    if spec is None:
        return None
    if isinstance(spec, tuple):
        return tuple(_rebuild_state(x, it) for x in spec)
    return next(it)


def _map_state(fn, new, old):
    """Apply fn(new_leaf, old_leaf) through a state structure (skip Nones)."""
    if new is None:
        return None
    if isinstance(new, tuple):
        return tuple(_map_state(fn, n, o) for n, o in zip(new, old))
    return fn(new, old)


def _cast(x, like):
    """Cast a traced f32 scalar to the compute dtype so jax's strong-dtype
    promotion doesn't silently upcast a bf16 update to f32 (eager python
    floats are weakly typed and keep the array dtype)."""
    return x.astype(like.dtype)


# ---------------------------------------------------------------------------
# per-optimizer lowerings.  Each takes the *optimizer instance* (for static
# hyperparameters), traced per-param scalars, and returns
# (new_weight, new_state, extras).  They call the same registered op
# functions the eager path invokes, so fused == oracle numerically.
# ---------------------------------------------------------------------------

_LOWER: Dict[type, object] = {}


def _lower(cls):
    def deco(fn):
        _LOWER[cls] = fn
        return fn
    return deco


def supports(optimizer) -> bool:
    return type(optimizer) in _LOWER


def _sgd_like_kw(opt, w, lr, wd, rescale):
    return dict(lr=_cast(lr, w), wd=_cast(wd, w), rescale_grad=_cast(rescale, w),
                clip_gradient=opt.clip_gradient)


from .optimizer import (SGD, NAG, Adam, AdamW, LAMB, RMSProp, AdaGrad,
                        AdaDelta, Ftrl, FTML, Signum, AdaMax, Nadam, SGLD,
                        DCASGD, LARS)


@_lower(SGD)
def _low_sgd(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    kw = _sgd_like_kw(opt, w, lr, wd, rescale)
    if st is None:
        return _f("sgd_update")(w, g, **kw), None, ex
    nw, nm = _f("sgd_mom_update")(w, g, st, momentum=opt.momentum, **kw)
    return nw, nm, ex


@_lower(NAG)
def _low_nag(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    nw, nm = _f("nag_mom_update")(w, g, st, momentum=opt.momentum,
                                  **_sgd_like_kw(opt, w, lr, wd, rescale))
    return nw, nm, ex


@_lower(Adam)
def _low_adam(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    coef = jnp.sqrt(1.0 - opt.beta2 ** t) / (1.0 - opt.beta1 ** t)
    m, v = st
    nw, nm, nv = _f("adam_update")(
        w, g, m, v, lr=_cast(lr * coef, w), beta1=opt.beta1, beta2=opt.beta2,
        epsilon=opt.epsilon, wd=_cast(wd, w), rescale_grad=_cast(rescale, w),
        clip_gradient=opt.clip_gradient)
    return nw, (nm, nv), ex


@_lower(AdamW)
def _low_adamw(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    coef = jnp.sqrt(1.0 - opt.beta2 ** t) / (1.0 - opt.beta1 ** t)
    m, v = st
    nw, nm, nv = _f("adamw_update")(
        w, g, m, v, lr=_cast(lr * coef, w), beta1=opt.beta1, beta2=opt.beta2,
        epsilon=opt.epsilon, wd=_cast(wd, w), eta=1.0,
        rescale_grad=_cast(rescale, w), clip_gradient=opt.clip_gradient)
    return nw, (nm, nv), ex


@_lower(LAMB)
def _low_lamb(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    m, v = st
    gd = _f("lamb_update_phase1")(
        w, g, m, v, beta1=opt.beta1, beta2=opt.beta2, epsilon=opt.epsilon,
        t=t, bias_correction=opt.bias_correction, wd=_cast(wd, w),
        rescale_grad=_cast(rescale, w), clip_gradient=opt.clip_gradient)
    gr = g * _cast(rescale, g)
    nm = opt.beta1 * m + (1 - opt.beta1) * gr
    nv = opt.beta2 * v + (1 - opt.beta2) * jnp.square(gr)
    r1 = _f("norm")(w)
    r2 = _f("norm")(gd)
    nw = _f("lamb_update_phase2")(w, gd, r1, r2, lr=_cast(lr, w),
                                  lower_bound=opt.lower_bound,
                                  upper_bound=opt.upper_bound)
    return nw, (nm, nv), ex


@_lower(RMSProp)
def _low_rmsprop(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    base = dict(lr=_cast(lr, w), wd=_cast(wd, w), gamma1=opt.gamma1,
                epsilon=opt.epsilon, rescale_grad=_cast(rescale, w),
                clip_gradient=opt.clip_gradient, clip_weights=opt.clip_weights)
    if opt.centered:
        n, g_, delta = st
        nw, nn, ng, nd = _f("rmspropalex_update")(w, g, n, g_, delta,
                                                  gamma2=opt.gamma2, **base)
        return nw, (nn, ng, nd), ex
    (n,) = st
    nw, nn = _f("rmsprop_update")(w, g, n, **base)
    return nw, (nn,), ex


@_lower(AdaGrad)
def _low_adagrad(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    nw, nh = _f("adagrad_update")(w, g, st, lr=_cast(lr, w), wd=_cast(wd, w),
                                  epsilon=opt.float_stable_eps,
                                  rescale_grad=_cast(rescale, w),
                                  clip_gradient=opt.clip_gradient)
    return nw, nh, ex


@_lower(AdaDelta)
def _low_adadelta(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    acc_g, acc_d = st
    nw, ng, nd = _f("adadelta_update")(w, g, acc_g, acc_d, rho=opt.rho,
                                       epsilon=opt.epsilon, wd=_cast(wd, w),
                                       rescale_grad=_cast(rescale, w),
                                       clip_gradient=opt.clip_gradient)
    return nw, (ng, nd), ex


@_lower(Ftrl)
def _low_ftrl(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    z, n = st
    nw, nz, nn = _f("ftrl_update")(w, g, z, n, lr=_cast(lr, w),
                                   lamda1=opt.lamda1, beta=opt.beta,
                                   wd=_cast(wd, w),
                                   rescale_grad=_cast(rescale, w),
                                   clip_gradient=opt.clip_gradient)
    return nw, (nz, nn), ex


@_lower(FTML)
def _low_ftml(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    d, v, z = st
    nw, nd, nv, nz = _f("ftml_update")(w, g, d, v, z, lr=_cast(lr, w),
                                       beta1=opt.beta1, beta2=opt.beta2,
                                       epsilon=opt.epsilon, t=t,
                                       wd=_cast(wd, w),
                                       rescale_grad=_cast(rescale, w),
                                       clip_grad=opt.clip_gradient)
    return nw, (nd, nv, nz), ex


@_lower(Signum)
def _low_signum(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    kw = _sgd_like_kw(opt, w, lr, wd, rescale)
    if st is None:
        return _f("signsgd_update")(w, g, **kw), None, ex
    nw, nm = _f("signum_update")(w, g, st, momentum=opt.momentum,
                                 wd_lh=opt.wd_lh, **kw)
    return nw, nm, ex


@_lower(AdaMax)
def _low_adamax(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    m, u = st
    nw, nm, nu = _f("adamax_update")(w, g, m, u, lr=_cast(lr, w),
                                     beta1=opt.beta1, beta2=opt.beta2,
                                     wd=_cast(wd, w), t=t,
                                     rescale_grad=_cast(rescale, w),
                                     clip_gradient=opt.clip_gradient)
    return nw, (nm, nu), ex


@_lower(Nadam)
def _low_nadam(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    m, v = st
    ms = ex["m_schedule"]
    nw, nm, nv = _f("nadam_update")(
        w, g, m, v, lr=_cast(lr, w), beta1=opt.beta1, beta2=opt.beta2,
        epsilon=opt.epsilon, wd=_cast(wd, w), t=t,
        schedule_decay=opt.schedule_decay, m_schedule=ms,
        rescale_grad=_cast(rescale, w), clip_gradient=opt.clip_gradient)
    # the eager path multiplies m_schedule once per *parameter* update — keep
    # that exact (quirky, reference-matching) sequence through the loop
    momentum_t = opt.beta1 * (1 - 0.5 * 0.96 ** (t * opt.schedule_decay))
    ex = dict(ex, m_schedule=ms * momentum_t)
    return nw, (nm, nv), ex


@_lower(SGLD)
def _low_sgld(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    from ..ops.optimizer_ops import _grad_prep

    g2 = _grad_prep(g, _cast(wd, w), w, _cast(rescale, w), opt.clip_gradient)
    noise = jax.random.normal(ex["keys"][pos], w.shape, w.dtype) \
        * jnp.sqrt(jnp.asarray(lr, w.dtype))
    return w - 0.5 * _cast(lr, w) * g2 + noise, None, ex


@_lower(DCASGD)
def _low_dcasgd(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    mom, prev = st
    nw, nm, nprev = _f("dcasgd_update")(w, g, mom, prev, lr=_cast(lr, w),
                                        momentum=opt.momentum, lamda=opt.lamda,
                                        wd=_cast(wd, w),
                                        rescale_grad=_cast(rescale, w),
                                        clip_gradient=opt.clip_gradient)
    return nw, (nm, nprev), ex


@_lower(LARS)
def _low_lars(opt, w, g, st, lr, wd, t, rescale, ex, pos):
    nw, nm = _f("lars_update")(w, g, st, lr=_cast(lr, w), momentum=opt.momentum,
                               eta=opt.eta, epsilon=opt.epsilon,
                               wd=_cast(wd, w), rescale_grad=_cast(rescale, w),
                               clip_gradient=opt.clip_gradient)
    return nw, nm, ex


# ---------------------------------------------------------------------------
# optimizer-global "extras": device scalars threaded through the per-param
# loop (Nadam's momentum schedule) or per-step inputs (SGLD's noise keys,
# pre-drawn from the SAME global stream the eager ops consume).
# ---------------------------------------------------------------------------

def _extras_prep(opt, n):
    if isinstance(opt, Nadam):
        ms = opt.m_schedule
        val = ms._data if isinstance(ms, NDArray) else jnp.float32(ms)
        return {"m_schedule": val}
    if isinstance(opt, SGLD):
        from .. import random as _random

        return {"keys": jnp.stack([_random.next_key() for _ in range(n)])}
    return {}


def _extras_finalize(opt, ex):
    if isinstance(opt, Nadam) and "m_schedule" in ex:
        # device-resident; checkpoint capture float()s it at save time only
        opt.m_schedule = NDArray(ex["m_schedule"])


def lower_update(opt, w, g, state, lr, wd=0.0, t=1, rescale=1.0, extras=None,
                 pos=0):
    """Apply one parameter's update as pure jax — the building block shared
    by the engine and parallel.ShardedTrainer (so the two can't diverge).
    ``state`` uses the eager Updater layout (None / array / tuple)."""
    fn = _LOWER.get(type(opt))
    if fn is None:
        raise NotImplementedError(
            f"no fused lowering for {type(opt).__name__}")
    to32 = lambda x: jnp.asarray(x, jnp.float32)  # noqa: E731
    ex = _extras_prep(opt, pos + 1) if extras is None else extras
    return fn(opt, w, g, state, to32(lr), to32(wd), to32(t), to32(rescale),
              ex, pos)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class FusedUpdateEngine:
    """Compiles and dispatches the one-program-per-step update.

    One engine per :class:`Updater`; the compile cache is keyed on the static
    parts of the update (optimizer class + scalar hyperparameters, state
    structure, array avals, scaler/clip toggles) while per-step scalars are
    traced.  ``compile_log`` records one entry per compilation for the
    TraceLinter's churn diagnosis; ``exec_count`` counts dispatches.
    """

    def __init__(self, optimizer, donate: Optional[bool] = None):
        self.optimizer = optimizer
        self._cache: Dict = {}
        self._donate = _donate_default() if donate is None else bool(donate)
        self.exec_count = 0
        self.compile_log: List[dict] = []
        self._costs: Dict = {}  # cache key -> device cost record
        # training-health plane (obs/health.py): when active, the step
        # program also emits device-resident numerics stats; both stay
        # device-side (zero syncs) until a sampled step batch-fetches them
        self.last_health: Optional[dict] = None
        self._skip_streak = np.int32(0)  # AMP consecutive-skip counter

    # -- keys --------------------------------------------------------------
    _TRACED_ATTRS = frozenset({"lr", "rescale_grad", "num_update",
                               "begin_num_update", "m_schedule", "wd",
                               "multi_precision"})

    def _static_key(self):
        opt = self.optimizer
        return tuple(sorted(
            (k, v) for k, v in opt.__dict__.items()
            if k not in self._TRACED_ATTRS and isinstance(v, (int, float, bool, str))))

    @staticmethod
    def _aval(x):
        return (tuple(x.shape), str(x.dtype))

    def supported(self) -> bool:
        return type(self.optimizer) in _LOWER

    # -- dispatch ----------------------------------------------------------
    def apply(self, indices, weights, grads, states, loss_scaler=None,
              clip_global_norm=None):
        """Run one fused update step over the given parameter set.

        ``weights``/``grads``/``states`` are parallel lists; states use the
        Updater slot layout and are updated in place (``_set_data`` rebinds
        the NDArray wrappers onto the program's outputs, so the optimizer
        state stays device-resident between steps).
        """
        opt = self.optimizer
        if not self.supported():
            raise NotImplementedError(
                f"no fused lowering for {type(opt).__name__}")
        n = len(indices)
        # host bookkeeping — identical order to the eager _common() sequence
        for i in indices:
            opt._update_count(i)
        lrs = np.asarray([opt._get_lr(i) for i in indices], np.float32)
        wds = np.asarray([opt._get_wd(i) for i in indices], np.float32)
        ts = np.asarray([opt._index_update_count[i] for i in indices],
                        np.float32)
        rescale = np.float32(opt.rescale_grad)

        mp = tuple(bool(opt._use_mp(w)) for w in weights)
        specs = tuple(_state_spec(s) for s in states)
        ws = tuple(w._data for w in weights)
        gs = tuple(g._data for g in grads)
        state_leaves = []
        for s in states:
            lv: list = []
            _state_leaves(s, lv)
            state_leaves.append(tuple(x._data for x in lv))
        state_leaves = tuple(state_leaves)

        scaler_on = loss_scaler is not None
        cgn_on = clip_global_norm is not None and clip_global_norm > 0
        if scaler_on:
            sc = loss_scaler.loss_scale
            scale = sc._data if isinstance(sc, NDArray) else np.float32(sc)
            un = getattr(loss_scaler, "_unskipped", 0)
            unskipped = un._data if isinstance(un, NDArray) else np.int32(un)
            factor = float(loss_scaler._factor)
            window = int(loss_scaler._window)
        else:
            scale, unskipped, factor, window = np.float32(1), np.int32(0), 2.0, 0
        cgn_val = np.float32(clip_global_norm if cgn_on else 0.0)
        extras = _extras_prep(opt, n)
        # health stats are part of the program (extra outputs, zero extra
        # dispatches) — the flag is a compile static, so a monitor-gated
        # loop alternates between exactly TWO cached variants (stats on
        # sampled steps, plain otherwise; updates bitwise-identical)
        health_on = obs.health.stats_for_this_step()
        streak_in = self._skip_streak if scaler_on else np.int32(0)

        key = (type(opt), self._static_key(), specs, mp,
               tuple(self._aval(x) for x in ws),
               tuple(self._aval(x) for x in gs),
               tuple(tuple(self._aval(x) for x in lp) for lp in state_leaves),
               scaler_on, factor, window, cgn_on, health_on, self._donate)
        _device = obs.device

        rec = obs.enabled()
        t0 = time.monotonic() if rec else 0.0
        jitted = self._cache.get(key)
        is_compile = jitted is None
        if is_compile:
            example = (ws, gs, state_leaves, lrs, wds, ts, rescale,
                       scale, unskipped, streak_in, cgn_val, extras)
            jitted, entry = self._compile(key, example)
            self._cache[key] = jitted
            self.compile_log.append(entry)
            # telemetry: every compile counts; a compile AFTER the first is
            # a retrace (something static churned — the TraceLinter's
            # update-retrace-churn rule diagnoses which component)
            obs.inc("update.cache_hit" if entry.get("cache_hit")
                    else "update.compile")
            if len(self.compile_log) > 1:
                obs.inc("update.retrace")

        from .. import profiler

        if profiler.counting_dispatches():
            profiler.count_dispatch("compiled")
            profiler.count_dispatch("h2d")  # the packed lr/wd/t hyper vectors
        with obs.trace.span("update.fused", optimizer=type(opt).__name__,
                            n_params=n, compile=is_compile) as sp:
            new_ws, new_flat, new_ex, scaler_out, health_out = jitted(
                ws, gs, state_leaves, lrs, wds, ts, rescale, scale, unskipped,
                streak_in, cgn_val, extras)
            cost = self._costs.get(key) if rec and not is_compile else None
            if cost:
                # analytic MFU + roofline on the executed program's span
                # (compile calls excluded: their wall time is the
                # compiler). Block first: on async backends the dispatch
                # returns futures and MFU over dispatch latency would be
                # meaningless — accurate attribution costs the overlap,
                # the profiler aggregate_stats trade
                jax.block_until_ready(new_ws)
                _device.annotate_span(sp, "update", time.monotonic() - t0,
                                      cost)
        if rec:
            # first call traces+compiles (blocking); later calls dispatch —
            # wall time only, UNLESS a cost record made the attribution
            # block above (then this is honest device time)
            obs.observe("update.compile_seconds" if is_compile
                        else "update.execute_seconds",
                        time.monotonic() - t0)
        self.exec_count += 1

        for w, nw in zip(weights, new_ws):
            w._set_data(nw)
        for s, leaves_new in zip(states, new_flat):
            old: list = []
            _state_leaves(s, old)
            for nd, nv in zip(old, leaves_new):
                nd._set_data(nv)
        _extras_finalize(opt, new_ex)
        if scaler_on:
            found, nsc, nun, nstreak = scaler_out
            loss_scaler.loss_scale = NDArray(nsc)
            loss_scaler._unskipped = NDArray(nun)
            loss_scaler.last_overflow = NDArray(found)  # device flag, no sync
            # consecutive-skip streak, maintained in-graph: the silent AMP
            # skip-loop (counters advance on skip) finally has a signal —
            # obs/health.py samples it and breaches on a long streak
            loss_scaler.skip_streak = NDArray(nstreak)
            self._skip_streak = nstreak
        if health_out is not None:
            g_all, g_norms, w_norms, u_norms, nonfin = health_out
            self.last_health = {
                "global_grad_norm": g_all, "grad_norms": g_norms,
                "param_norms": w_norms, "update_norms": u_norms,
                "nonfinite": nonfin, "indices": tuple(indices)}
            if scaler_on:
                self.last_health["found_inf"] = scaler_out[0]
                self.last_health["skip_streak"] = scaler_out[3]
        else:
            self.last_health = None

    # -- persistent program cache -----------------------------------------
    def _program_key(self, key):
        """The fused step's :class:`~mxnet_tpu.progcache.ProgramKey` —
        the in-process memo ``key`` canonicalized through the ONE shared
        derivation (``progcache.program_key``), so the device-plane cost
        registry, this engine's ``compile_log``, and the persistent cache
        agree on the program's identity byte for byte."""
        from .. import progcache as _progcache

        return _progcache.program_key("update", type(self.optimizer).__name__,
                                      key)

    def _compile(self, key, example):
        """Resolve one cache-key miss to an executable + its compile_log
        entry: persistent-cache hit (deserialize the stored executable —
        zero fresh XLA work) > AOT compile with device-cost capture >
        plain ``jax.jit``. A corrupt/stale/foreign entry was already
        counted as a reject by the cache and lands here as a miss."""
        from .. import progcache as _progcache

        opt = self.optimizer
        (_, _, specs, mp, _, _, _, scaler_on, factor, window, cgn_on,
         health_on, _) = key
        entry = {
            "optimizer": type(opt).__name__,
            "static": self._static_key(),
            "avals": key[4],
            "state_structure": specs,
            "flags": (scaler_on, cgn_on, health_on),
        }
        _device = obs.device
        pc = _progcache.cache()
        pk = None
        if pc is not None:
            pk = self._program_key(key)
            entry["program_key"] = pk.digest
            cached = pc.get(pk)
            if cached is not None:
                entry["cache_hit"] = True
                cost = _device.adopt_cached_cost(pk, cached.meta)
                if cost:
                    entry.update(cost)
                    self._costs[key] = cost
                return cached.executable, entry
        entry["cache_hit"] = False
        jitted = self._build(specs, mp, scaler_on, factor, window, cgn_on,
                             health_on)
        compiled = cost = None
        if _device.active():
            # ONE compile serves accounting and execution: the AOT
            # executable replaces the jit wrapper in the cache, and its
            # XLA cost/memory analyses land in this compile_log entry
            compiled, cost = _device.capture(jitted, example, site="update",
                                             label=type(opt).__name__,
                                             key=pk)
        elif pc is not None:  # cache armed, cost capture vetoed: plain AOT
            compiled = _progcache.aot_compile(jitted, example)
            cost = (_device.analyze_compiled(compiled)
                    if compiled is not None else None)
        if compiled is not None:
            if pc is not None:
                pc.put(pk, compiled, meta=dict(cost or {}))
            jitted = compiled
        if cost:
            entry.update(cost)
            self._costs[key] = cost
        return jitted, entry

    def prewarm(self, indices, weights, grads, states, loss_scaler=None,
                clip_global_norm=None) -> bool:
        """Populate the compile cache for the step ``apply`` would run on
        these tensors — WITHOUT executing it or touching optimizer
        counters. The elastic-rejoin path calls this while quarantined so
        the compile/deserialize overlaps the wait for the activation
        boundary instead of stalling the fleet's first lockstep reduce.
        Returns True when the program is now cached (either source)."""
        opt = self.optimizer
        if not self.supported():
            return False
        n = len(indices)
        # example traced scalars only — values never shape the program
        lrs = np.zeros(n, np.float32)
        wds = np.zeros(n, np.float32)
        ts = np.ones(n, np.float32)
        rescale = np.float32(opt.rescale_grad)
        mp = tuple(bool(opt._use_mp(w)) for w in weights)
        specs = tuple(_state_spec(s) for s in states)
        ws = tuple(w._data for w in weights)
        gs = tuple(g._data for g in grads)
        state_leaves = []
        for s in states:
            lv: list = []
            _state_leaves(s, lv)
            state_leaves.append(tuple(x._data for x in lv))
        state_leaves = tuple(state_leaves)
        scaler_on = loss_scaler is not None
        cgn_on = clip_global_norm is not None and clip_global_norm > 0
        if scaler_on:
            factor = float(loss_scaler._factor)
            window = int(loss_scaler._window)
        else:
            factor, window = 2.0, 0
        health_on = obs.health.stats_for_this_step()
        key = (type(opt), self._static_key(), specs, mp,
               tuple(self._aval(x) for x in ws),
               tuple(self._aval(x) for x in gs),
               tuple(tuple(self._aval(x) for x in lp) for lp in state_leaves),
               scaler_on, factor, window, cgn_on, health_on, self._donate)
        if key in self._cache:
            return True
        example = (ws, gs, state_leaves, lrs, wds, ts, rescale,
                   np.float32(1), np.int32(0), np.int32(0),
                   np.float32(clip_global_norm if cgn_on else 0.0),
                   _extras_prep(opt, n))
        jitted, entry = self._compile(key, example)
        self._cache[key] = jitted
        self.compile_log.append(entry)
        obs.event("progcache.prewarm", optimizer=type(opt).__name__,
                  cache_hit=bool(entry.get("cache_hit")))
        return True

    # -- compile -----------------------------------------------------------
    def _build(self, specs, mp, scaler_on, factor, window, cgn_on,
               health_on=False):
        opt = self.optimizer
        lowering = _LOWER[type(opt)]

        def step(ws, gs, state_leaves, lrs, wds, ts, rescale, scale,
                 unskipped, streak, cgn, extras):
            gs = list(gs)
            found = jnp.zeros((), jnp.bool_)
            if scaler_on:
                inv = 1.0 / scale
                gs = [g * inv.astype(g.dtype) for g in gs]
            nonfin = None
            if health_on:
                # per-grad non-finite counts; the scaler's found-inf
                # reduction is their OR — one pass serves both signals
                nonfin = [jnp.sum(
                    (~jnp.isfinite(g.astype(jnp.float32))).astype(jnp.int32))
                    for g in gs]
                if scaler_on:
                    for c in nonfin:
                        found = found | (c > 0)
            elif scaler_on:
                for g in gs:
                    found = found | ~jnp.all(jnp.isfinite(
                        g.astype(jnp.float32)))
            gsqs, gnorm = None, None
            if cgn_on or health_on:
                # ONE reduction serves clipping AND the health plane's
                # global/per-param grad norms (pre-clip, post-unscale —
                # the raw explosion signal)
                gsqs = [jnp.sum(jnp.square(g.astype(jnp.float32) * rescale))
                        for g in gs]
                sq = jnp.float32(0.0)
                for s in gsqs:
                    sq = sq + s
                gnorm = jnp.sqrt(sq)
            if cgn_on:
                coef = jnp.minimum(jnp.float32(1.0), cgn / (gnorm + 1e-6))
                gs = [g * coef.astype(g.dtype) for g in gs]

            new_ws, new_states = [], []
            ex = extras
            for pos in range(len(ws)):
                w, g = ws[pos], gs[pos]
                st = _rebuild_state(specs[pos], iter(state_leaves[pos]))
                lr_i, wd_i, t_i = lrs[pos], wds[pos], ts[pos]
                if mp[pos]:
                    inner, w32 = st
                    nw32, ninner, ex = lowering(opt, w32, g.astype(jnp.float32),
                                                inner, lr_i, wd_i, t_i,
                                                rescale, ex, pos)
                    nw = nw32.astype(w.dtype)
                    nstate = (ninner, nw32)
                else:
                    nw, nstate, ex = lowering(opt, w, g, st, lr_i, wd_i, t_i,
                                              rescale, ex, pos)
                    nw = nw.astype(w.dtype)
                new_ws.append(nw)
                new_states.append(nstate)

            if scaler_on:
                # nonfinite grads: keep params/states, shrink the scale — all
                # selected on-device, zero host round-trips
                sel = lambda new, old: jnp.where(found, old, new)  # noqa: E731
                new_ws = [sel(nw, w) for nw, w in zip(new_ws, ws)]
                new_states = [
                    _map_state(sel, ns,
                               _rebuild_state(specs[i],
                                              iter(state_leaves[i])))
                    for i, ns in enumerate(new_states)]
                ex = {k: (sel(v, extras[k]) if k != "keys" else v)
                      for k, v in ex.items()}
                nskip = unskipped + 1
                grow = nskip >= window
                new_scale = jnp.where(
                    found, jnp.maximum(scale / factor, 1e-4),
                    jnp.where(grow, jnp.minimum(scale * factor, 2.0 ** 24),
                              scale))
                new_unskipped = jnp.where(found | grow, 0, nskip).astype(
                    jnp.asarray(unskipped).dtype)
                new_streak = jnp.where(found, streak + 1, 0).astype(
                    jnp.int32)
                scaler_out = (found, new_scale, new_unskipped, new_streak)
            else:
                scaler_out = None

            health_out = None
            if health_on:
                # device-resident numerics scalars, emitted as extra
                # outputs of THIS program — zero extra dispatches; update
                # norms measure the applied step (0 on a scaler skip)
                f32 = jnp.float32
                w_norms = [jnp.sqrt(jnp.sum(jnp.square(w.astype(f32))))
                           for w in ws]
                u_norms = [jnp.sqrt(jnp.sum(jnp.square(
                    nw.astype(f32) - w.astype(f32))))
                    for nw, w in zip(new_ws, ws)]
                health_out = (gnorm,
                              jnp.stack([jnp.sqrt(s) for s in gsqs]),
                              jnp.stack(w_norms), jnp.stack(u_norms),
                              jnp.stack(nonfin))

            flat_new = []
            for ns in new_states:
                lv: list = []
                _state_leaves(ns, lv)
                flat_new.append(tuple(lv))
            return tuple(new_ws), tuple(flat_new), ex, scaler_out, health_out

        donate = (0, 2) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)
