"""Optimizers — thin state machines over the fused update ops.

Reference: ``python/mxnet/optimizer/optimizer.py`` + the fused kernels in
``src/operator/optimizer_op.*`` (TBV — SURVEY.md §2.2/§2.3). The TPU analog of
"fused update kernel" is that each update is one registered pure op; when the
whole train step is jitted (Module / fused Trainer path) XLA fuses all
parameter updates into the step program.

API parity: create-by-name registry, ``update(index, weight, grad, state)``,
multi-precision (fp16/bf16 weights with fp32 master copy), lr/wd multipliers,
``set_learning_rate``, Updater for kvstore server-side application.
"""
from __future__ import annotations

import numpy as np

from ..ndarray import NDArray, zeros
from ..ndarray.ndarray import invoke

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "LAMB", "RMSProp", "AdaGrad",
           "AdaDelta", "Ftrl", "FTML", "Signum", "create", "register", "Updater",
           "get_updater"]

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    name = name.lower()
    if name not in _REGISTRY:
        raise ValueError(f"unknown optimizer {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


class Optimizer:
    def __init__(self, learning_rate=0.01, rescale_grad=1.0, clip_gradient=None,
                 lr_scheduler=None, wd=0.0, momentum=0.0, param_idx2name=None,
                 multi_precision=False, param_dict=None, begin_num_update=0, **kwargs):
        self.lr = learning_rate
        self.rescale_grad = rescale_grad
        self.clip_gradient = clip_gradient if clip_gradient is not None else -1.0
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count = {}
        self.idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    # -- bookkeeping -----------------------------------------------------
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        p = self.param_dict.get(index)
        if p is not None:
            lr *= p.lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        p = self.param_dict.get(index)
        if p is not None:
            wd *= p.wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise RuntimeError("cannot set lr directly when lr_scheduler is active")
        self.lr = lr

    @property
    def learning_rate(self):
        return self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    # -- state -----------------------------------------------------------
    def _use_mp(self, weight):
        return self.multi_precision and weight.dtype in (np.float16,) or \
            (self.multi_precision and str(weight.dtype) == "bfloat16")

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self._use_mp(weight):
            w32 = weight.astype(np.float32)
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self._use_mp(weight):
            inner_state, w32 = state
            g32 = grad.astype(np.float32)
            self.update(index, w32, g32, inner_state)
            weight._set_data(w32.astype(weight.dtype)._data)
        else:
            self.update(index, weight, grad, state)

    def _common(self, index):
        self._update_count(index)
        return self._get_lr(index), self._get_wd(index)


@register
class SGD(Optimizer):
    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)
        return None

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient)
        if state is not None:
            invoke("sgd_mom_update", [weight, grad, state],
                   {**kw, "momentum": self.momentum,
                    "out": (weight, state)})
        else:
            invoke("sgd_update", [weight, grad], {**kw, "out": weight})


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        invoke("nag_mom_update", [weight, grad, state],
               {"lr": lr, "wd": wd, "momentum": self.momentum,
                "rescale_grad": self.rescale_grad, "clip_gradient": self.clip_gradient,
                "out": (weight, state)})


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        t = self._index_update_count[index]
        lr *= np.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        mean, var = state
        invoke("adam_update", [weight, grad, mean, var],
               {"lr": lr, "beta1": self.beta1, "beta2": self.beta2,
                "epsilon": self.epsilon, "wd": wd, "rescale_grad": self.rescale_grad,
                "clip_gradient": self.clip_gradient, "out": (weight, mean, var)})


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay (reference contrib adamw_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        t = self._index_update_count[index]
        coef = float(np.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t))
        mean, var = state
        invoke("adamw_update", [weight, grad, mean, var],
               {"lr": lr * coef, "beta1": self.beta1, "beta2": self.beta2,
                "epsilon": self.epsilon, "wd": wd, "eta": 1.0,
                "rescale_grad": self.rescale_grad,
                "clip_gradient": self.clip_gradient, "out": (weight, mean, var)})


@register
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (reference lamb_update_*)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 lower_bound=None, upper_bound=None, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound = lower_bound if lower_bound is not None else -1.0
        self.upper_bound = upper_bound if upper_bound is not None else -1.0
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        t = self._index_update_count[index]
        mean, var = state
        g = invoke("lamb_update_phase1", [weight, grad, mean, var],
                   {"beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon,
                    "t": t, "bias_correction": self.bias_correction, "wd": wd,
                    "rescale_grad": self.rescale_grad,
                    "clip_gradient": self.clip_gradient})
        # phase1 returns only the update direction; recompute m/v for state
        mean._set_data((self.beta1 * mean + (1 - self.beta1) *
                        (grad * self.rescale_grad))._data)
        var._set_data((self.beta2 * var + (1 - self.beta2) *
                       (grad * self.rescale_grad) ** 2)._data)
        r1 = weight.norm()
        r2 = g.norm()
        invoke("lamb_update_phase2", [weight, g, r1, r2],
               {"lr": lr, "lower_bound": self.lower_bound,
                "upper_bound": self.upper_bound, "out": weight})


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2, self.epsilon = gamma1, gamma2, epsilon
        self.centered = centered
        self.clip_weights = clip_weights if clip_weights is not None else -1.0

    def create_state(self, index, weight):
        z = lambda: zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        base = {"lr": lr, "wd": wd, "gamma1": self.gamma1, "epsilon": self.epsilon,
                "rescale_grad": self.rescale_grad, "clip_gradient": self.clip_gradient,
                "clip_weights": self.clip_weights}
        if self.centered:
            n, g_, delta = state
            invoke("rmspropalex_update", [weight, grad, n, g_, delta],
                   {**base, "gamma2": self.gamma2, "out": (weight, n, g_, delta)})
        else:
            (n,) = state
            invoke("rmsprop_update", [weight, grad, n], {**base, "out": (weight, n)})


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=0.01, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        invoke("adagrad_update", [weight, grad, state],
               {"lr": lr, "wd": wd, "epsilon": self.float_stable_eps,
                "rescale_grad": self.rescale_grad, "clip_gradient": self.clip_gradient,
                "out": (weight, state)})


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        _, wd = self._common(index)
        acc_g, acc_d = state
        invoke("adadelta_update", [weight, grad, acc_g, acc_d],
               {"rho": self.rho, "epsilon": self.epsilon, "wd": wd,
                "rescale_grad": self.rescale_grad, "clip_gradient": self.clip_gradient,
                "out": (weight, acc_g, acc_d)})


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        z, n = state
        invoke("ftrl_update", [weight, grad, z, n],
               {"lr": lr, "lamda1": self.lamda1, "beta": self.beta, "wd": wd,
                "rescale_grad": self.rescale_grad, "clip_gradient": self.clip_gradient,
                "out": (weight, z, n)})


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = lambda: zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)
        return (z(), z(), z())

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        t = self._index_update_count[index]
        d, v, z = state
        invoke("ftml_update", [weight, grad, d, v, z],
               {"lr": lr, "beta1": self.beta1, "beta2": self.beta2,
                "epsilon": self.epsilon, "t": t, "wd": wd,
                "rescale_grad": self.rescale_grad, "clip_grad": self.clip_gradient,
                "out": (weight, d, v, z)})


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)
        return None

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        if state is not None:
            invoke("signum_update", [weight, grad, state],
                   {"lr": lr, "wd": wd, "momentum": self.momentum, "wd_lh": self.wd_lh,
                    "rescale_grad": self.rescale_grad,
                    "clip_gradient": self.clip_gradient, "out": (weight, state)})
        else:
            invoke("signsgd_update", [weight, grad],
                   {"lr": lr, "wd": wd, "rescale_grad": self.rescale_grad,
                    "clip_gradient": self.clip_gradient, "out": weight})


class Updater:
    """Applies an optimizer to (index, grad, weight) triplets — the object the
    reference serializes to KVStore servers (set_optimizer).

    ``update_batch`` is the fused fast path: all parameters update in ONE
    compiled program per step (optimizer/fused.py) unless
    ``MXNET_FUSED_UPDATE=0`` selects the per-parameter eager oracle.
    ``__call__`` stays per-parameter (the kvstore per-key push surface).
    """

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self._engine = None

    def _get_engine(self):
        if self._engine is None:
            from .fused import FusedUpdateEngine

            self._engine = FusedUpdateEngine(self.optimizer)
        return self._engine

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad, self.states[index])

    def prewarm_batch(self, indices, weights, loss_scaler=None,
                      clip_global_norm=None) -> bool:
        """Compile (or deserialize from the persistent program cache) the
        fused step for this parameter set WITHOUT running it — optimizer
        counters, weights, and states are untouched. The elastic-rejoin
        path warms here while quarantined (docs/PERFORMANCE.md "Program
        cache and cold start"). Returns True when the program is cached."""
        from .fused import fused_update_enabled

        if not fused_update_enabled() or len(set(indices)) != len(indices):
            return False
        eng = self._get_engine()
        if not eng.supported():
            return False
        # existing states are reused; missing ones are built LOCALLY and
        # discarded — only their aval structure shapes the program, and
        # persisting a state derived from prewarm-time weights would seed
        # the real first update with a stale fp32 master copy
        states = [self.states.get(i) if i in self.states
                  else self.optimizer.create_state_multi_precision(i, w)
                  for i, w in zip(indices, weights)]
        grads = [w.zeros_like() for w in weights]
        return eng.prewarm(indices, weights, grads, states,
                           loss_scaler=loss_scaler,
                           clip_global_norm=clip_global_norm)

    def update_batch(self, indices, grads, weights, loss_scaler=None,
                     clip_global_norm=None):
        """Update a whole parameter set at once. Fused-by-default: one donated
        XLA program covers every optimizer update plus global-norm clipping
        and the AMP unscale/found-inf skip (docs/PERFORMANCE.md)."""
        from .fused import fused_update_enabled

        for i, w in zip(indices, weights):
            if i not in self.states:
                self.states[i] = self.optimizer.create_state_multi_precision(i, w)
        # a duplicate index (kvstore broadcast push(key, [v1, v2])) must apply
        # sequentially — the fused program reads all pre-step buffers up
        # front, so last-write-wins would drop the earlier updates
        if fused_update_enabled() and len(set(indices)) == len(indices):
            eng = self._get_engine()
            if eng.supported():
                eng.apply(indices, weights, grads,
                          [self.states[i] for i in indices],
                          loss_scaler=loss_scaler,
                          clip_global_norm=clip_global_norm)
                return
        self._eager_batch(indices, grads, weights, loss_scaler,
                          clip_global_norm)

    def _eager_batch(self, indices, grads, weights, loss_scaler=None,
                     clip_global_norm=None):
        """The per-parameter oracle: same semantics as the fused engine, one
        dispatch per op, host syncs allowed (differential-test reference)."""
        opt = self.optimizer
        gs = list(grads)
        skip = False
        if loss_scaler is not None:
            scale = float(loss_scaler.loss_scale)
            if scale != 1.0:
                gs = [g * (1.0 / scale) for g in gs]
            skip = _grads_nonfinite(gs)
        if not skip and clip_global_norm is not None and clip_global_norm > 0:
            rescale = float(opt.rescale_grad)
            sq = 0.0
            for g in gs:
                sq += float((g.astype(np.float32) * rescale).square().sum()
                            .asscalar())
            coef = min(1.0, float(clip_global_norm) /
                       (float(np.sqrt(np.float32(sq))) + 1e-6))
            if coef < 1.0:
                gs = [g * coef for g in gs]
        if skip:
            # counters advance on skipped steps (same as the fused engine)
            for i in indices:
                opt._update_count(i)
        else:
            for i, g, w in zip(indices, gs, weights):
                opt.update_multi_precision(i, w, g, self.states[i])
        if loss_scaler is not None:
            loss_scaler.loss_scale = float(loss_scaler.loss_scale)
            loss_scaler._unskipped = int(getattr(loss_scaler, "_unskipped", 0))
            loss_scaler.update_scale(skip)
            loss_scaler.last_overflow = skip
            # consecutive-skip streak — host-side mirror of the fused
            # engine's in-graph counter (obs/health.py samples it)
            prev = getattr(loss_scaler, "skip_streak", 0)
            if hasattr(prev, "asnumpy"):
                prev = int(prev.asnumpy())
            loss_scaler.skip_streak = (int(prev) + 1) if skip else 0

    def get_states(self, dump_optimizer=False):
        import pickle

        # ONE batched device→host transfer for all slots (not one blocking
        # asnumpy() per array): gather the jax leaves, device_get once,
        # then rebuild the nested numpy structure.
        import jax as _jax

        from .fused import _rebuild_state, _state_leaves, _state_spec

        leaves = []
        for v in self.states.values():
            _state_leaves(v, leaves)
        host = _jax.device_get([x._data for x in leaves])
        host_it = iter(np.asarray(h) for h in host)
        out = {k: _rebuild_state(_state_spec(v), host_it)
               for k, v in self.states.items()}
        return pickle.dumps(out)

    def set_states(self, states):
        import pickle

        loaded = pickle.loads(states)
        self.states = {k: _states_nd(v) for k, v in loaded.items()}


def _grads_nonfinite(grads) -> bool:
    """One batched finiteness reduction over all gradients (single sync)."""
    import jax as _jax
    import jax.numpy as _jnp

    flags = [_jnp.all(_jnp.isfinite(g._data.astype(_jnp.float32)))
             for g in grads]
    return not bool(np.all(_jax.device_get(flags)))


def _states_np(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_states_np(x) for x in s)
    return s.asnumpy()


def _states_nd(s):
    from ..ndarray import array

    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_states_nd(x) for x in s)
    return array(s)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)


@register
class AdaMax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        t = self._index_update_count[index]
        mean, inf_norm = state
        invoke("adamax_update", [weight, grad, mean, inf_norm],
               {"lr": lr, "beta1": self.beta1, "beta2": self.beta2, "wd": wd,
                "rescale_grad": self.rescale_grad, "t": t,
                "clip_gradient": self.clip_gradient,
                "out": (weight, mean, inf_norm)})


Adamax = AdaMax  # reference exposes both spellings


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        t = self._index_update_count[index]
        momentum_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        # the fused engine keeps m_schedule device-resident (a 0-d NDArray);
        # re-entering the eager path materializes it back to a python float
        if not isinstance(self.m_schedule, float):
            self.m_schedule = float(self.m_schedule)
        mean, var = state
        invoke("nadam_update", [weight, grad, mean, var],
               {"lr": lr, "beta1": self.beta1, "beta2": self.beta2,
                "epsilon": self.epsilon, "wd": wd, "t": t,
                "schedule_decay": self.schedule_decay,
                "m_schedule": self.m_schedule,
                "rescale_grad": self.rescale_grad,
                "clip_gradient": self.clip_gradient,
                "out": (weight, mean, var)})
        self.m_schedule *= momentum_t


@register
class SGLD(Optimizer):
    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        invoke("sgld_update", [weight, grad],
               {"lr": lr, "wd": wd, "rescale_grad": self.rescale_grad,
                "clip_gradient": self.clip_gradient, "out": weight})


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                weight.copy())

    def update(self, index, weight, grad, state):
        lr, wd = self._common(index)
        mom, prev = state
        invoke("dcasgd_update", [weight, grad, mom, prev],
               {"lr": lr, "momentum": self.momentum, "lamda": self.lamda,
                "wd": wd, "rescale_grad": self.rescale_grad,
                "clip_gradient": self.clip_gradient,
                "out": (weight, mom, prev)})


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference optimizer.LARS — the
    large-batch SGD variant): per-tensor trust ratio
    eta*||w|| / (||g|| + wd*||w|| + eps) scales the learning rate, then a
    plain momentum update applies."""

    def __init__(self, momentum=0.9, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        # trust-ratio norms are computed IN-GRAPH by the lars_update op — the
        # previous weight.asnumpy()/np.linalg.norm implementation forced two
        # blocking device→host transfers per parameter per step
        lr, wd = self._common(index)
        invoke("lars_update", [weight, grad, state],
               {"lr": lr, "momentum": self.momentum, "eta": self.eta,
                "epsilon": self.epsilon, "wd": wd,
                "rescale_grad": self.rescale_grad,
                "clip_gradient": self.clip_gradient,
                "out": (weight, state)})


Adamax = AdaMax  # reference spelling alias
_REGISTRY["adamax"] = AdaMax
