"""SLO monitor — service-level math over merged fleet telemetry
(docs/OBSERVABILITY.md "SLO monitoring").

Raw telemetry answers "what happened"; this module answers "are we keeping
our promises". It consumes a *merged* metrics snapshot (obs/export.py
``merge_metrics`` over every fleet member's registry — or a single
process's snapshot, same schema) plus optionally the router's stats dict,
and computes:

- **deadline attainment** — the fraction of finished requests that met
  their deadline: completions over completions + deadline sheds. The serve
  plane's contract is that an expired request is *shed, never executed
  late* (serve/batcher.py), so a deadline miss is precisely a
  ``serve.shed_deadline`` increment — attainment falls out of counters,
  no per-request log needed.
- **error-budget burn rate** — ``(1 - attainment) / (1 - target)``: burn
  1.0 spends the budget exactly at its allowance; burn 2.0 exhausts a
  30-day budget in 15 days. The standard multi-window alert input.
- **p99 latency** (bucket-resolution, from the merged
  ``serve.latency_seconds`` histogram) vs an optional target,
- **shed-by-reason rates**, **breaker open-time**, **hedge win rate** —
  the fleet-health signals PR 6 made observable per replica, aggregated.

Threshold callbacks: ``monitor.on_breach(fn)`` fires ``fn(report,
breaches)`` whenever an ``evaluate()`` crosses a threshold — the hook a
pager/autoscaler attaches to. The monitor is deliberately pull-based
(evaluate on each telemetry collection); it owns no thread.
"""
from __future__ import annotations

from typing import Callable, List, Optional

from .export import hist_quantile

__all__ = ["SLOMonitor"]

_SHED_REASONS = ("deadline", "queue_full", "draining")


class SLOMonitor:
    """Compute SLO attainment / burn / tail-latency from a metrics
    snapshot and fire callbacks on threshold breaches.

    Parameters
    ----------
    deadline_target : float
        The SLO: fraction of requests that must meet their deadline
        (default 0.99 — "three nines of four" is a different monitor).
    p99_target_ms : float, optional
        Alert threshold on the merged p99 latency.
    burn_alert : float
        Breach when the error-budget burn rate exceeds this (default 2.0:
        the budget is being spent at twice its sustainable pace).
    breaker_open_alert_s : float, optional
        Breach when cumulative breaker open-time exceeds this.
    latency_metric : str
        Histogram name carrying end-to-end latency
        (default ``serve.latency_seconds``).
    """

    def __init__(self, deadline_target: float = 0.99,
                 p99_target_ms: Optional[float] = None,
                 burn_alert: float = 2.0,
                 breaker_open_alert_s: Optional[float] = None,
                 latency_metric: str = "serve.latency_seconds"):
        if not 0.0 < deadline_target < 1.0:
            raise ValueError("deadline_target must be in (0, 1)")
        self.deadline_target = float(deadline_target)
        self.p99_target_ms = p99_target_ms
        self.burn_alert = float(burn_alert)
        self.breaker_open_alert_s = breaker_open_alert_s
        self.latency_metric = latency_metric
        self._callbacks: List[Callable] = []
        self.last_report: Optional[dict] = None

    def on_breach(self, fn: Callable) -> "SLOMonitor":
        """Register ``fn(report, breaches)``; returns self for chaining."""
        self._callbacks.append(fn)
        return self

    # ------------------------------------------------------------------
    def evaluate(self, snapshot: dict, stats: Optional[dict] = None) -> dict:
        """One pass over a (merged) metrics snapshot → the SLO report.
        ``stats`` is the router/fleet stats dict when available (breaker
        open-time lives there too; the metrics gauge is used otherwise)."""
        counters = snapshot.get("counters") or {}
        hists = snapshot.get("histograms") or {}

        # prefer the ROUTER's per-request histogram when a fleet is in the
        # snapshot: replica-side serve.latency_seconds counts executions,
        # which hedging duplicates (the discarded loser still observed) —
        # attainment must be over requests, not executions. The miss
        # counter pairs with whichever source is used.
        fleet_lat = hists.get("fleet.request_latency_seconds")
        if fleet_lat is not None:
            lat = fleet_lat
            misses = counters.get("fleet.request_deadline_exceeded", 0)
        else:
            lat = hists.get(self.latency_metric)
            misses = counters.get("serve.shed_deadline", 0)
        completed = lat.get("count", 0) if lat else 0
        sheds = {r: counters.get(f"serve.shed_{r}", 0)
                 for r in _SHED_REASONS}
        shed_total = sum(sheds.values())
        finished = completed + shed_total
        # attainment over requests that HAD a deadline verdict (completed
        # or deadline-shed): queue_full/draining rejections are capacity
        # failures, tracked by shed_rate — folding them into this
        # denominator would DILUTE misses and keep the pager silent
        # exactly when the fleet is saturated
        denom = completed + misses
        attainment = 1.0 - (misses / denom) if denom else 1.0
        budget = 1.0 - self.deadline_target
        burn = ((1.0 - attainment) / budget) if budget else 0.0

        p99_s = hist_quantile(lat, 0.99) if lat else 0.0
        p50_s = hist_quantile(lat, 0.50) if lat else 0.0

        hedges = counters.get("fleet.hedges", 0)
        hedge_wins = counters.get("fleet.hedge_wins", 0)
        gauges = snapshot.get("gauges") or {}
        if stats and "breaker_open_seconds" in stats:
            breaker_open = float(stats["breaker_open_seconds"])
        else:
            breaker_open = float(
                gauges.get("fleet.breaker_open_seconds", 0.0))
        # a total outage makes NO latency observations and NO sheds —
        # attainment alone would read 1.0 while every client errors; the
        # ready-replica count and hard-error counters close that blind
        # spot (None when the snapshot carries no fleet at all)
        if stats and "ready_replicas" in stats:
            ready_replicas = int(stats["ready_replicas"])
        elif "fleet.ready_replicas" in gauges:
            ready_replicas = int(gauges["fleet.ready_replicas"])
        else:
            ready_replicas = None
        execute_errors = counters.get("serve.execute_errors", 0)

        report = {
            "requests_finished": finished,
            "completed": completed,
            "deadline_misses": misses,
            "deadline_attainment": round(attainment, 6),
            "deadline_target": self.deadline_target,
            "error_budget_burn": round(burn, 4),
            "p50_latency_ms": round(p50_s * 1e3, 3),
            "p99_latency_ms": round(p99_s * 1e3, 3),
            "shed_by_reason": sheds,
            "shed_rate": round(shed_total / finished, 6) if finished else 0.0,
            "breaker_trips": counters.get("fleet.breaker_trips", 0),
            "breaker_open_seconds": round(breaker_open, 3),
            "ready_replicas": ready_replicas,
            "execute_errors": execute_errors,
            "failovers": counters.get("fleet.failovers", 0),
            "hedges": hedges,
            "hedge_win_rate": round(hedge_wins / hedges, 4) if hedges
            else None,
            "stale_version_rejected":
                counters.get("fleet.stale_version_rejected", 0),
        }

        breaches = []
        if ready_replicas == 0:
            breaches.append({
                "rule": "no_ready_replicas",
                "value": 0, "threshold": 1,
                "detail": "0 ready replicas — total outage (no latency/"
                          "shed signal will be produced; attainment is "
                          "meaningless until capacity returns)"})
        if finished and attainment < self.deadline_target:
            breaches.append({
                "rule": "deadline_attainment",
                "value": attainment, "threshold": self.deadline_target,
                "detail": f"attainment {attainment:.4f} < target "
                          f"{self.deadline_target}"})
        if finished and burn > self.burn_alert:
            breaches.append({
                "rule": "error_budget_burn",
                "value": burn, "threshold": self.burn_alert,
                "detail": f"burn {burn:.2f}x > alert {self.burn_alert}x"})
        if (self.p99_target_ms is not None and lat
                and p99_s * 1e3 > self.p99_target_ms):
            breaches.append({
                "rule": "p99_latency",
                "value": p99_s * 1e3, "threshold": self.p99_target_ms,
                "detail": f"p99 {p99_s * 1e3:.1f}ms > "
                          f"{self.p99_target_ms}ms"})
        if (self.breaker_open_alert_s is not None
                and breaker_open > self.breaker_open_alert_s):
            breaches.append({
                "rule": "breaker_open_time",
                "value": breaker_open,
                "threshold": self.breaker_open_alert_s,
                "detail": f"breakers open {breaker_open:.1f}s > "
                          f"{self.breaker_open_alert_s}s"})
        report["breaches"] = breaches
        report["ok"] = not breaches
        self.last_report = report

        if breaches:
            try:  # an SLO breach is a flight-recorder moment: capture the
                # fleet's last seconds while they are still in the ring
                # (throttled; no-op unless the recorder is armed)
                from . import blackbox

                blackbox.trigger(
                    "slo_breach:" + ",".join(b["rule"] for b in breaches))
            except Exception:  # noqa: BLE001 — telemetry must not fail SLO
                pass
            for fn in self._callbacks:
                try:
                    fn(report, breaches)
                except Exception:  # noqa: BLE001 — a pager hook must never
                    pass           # take down the telemetry plane
        return report

    # ------------------------------------------------------------------
    def burn_window(self, prev: Optional[dict], cur: dict) -> dict:
        """Error-budget burn over the WINDOW between two metrics snapshots
        (counter deltas) — the autoscaler's signal (serve/autoscale.py).
        Cumulative burn never cools down after an incident, so a scaler
        fed :meth:`evaluate` would keep scaling out forever; a window
        recovers the moment the fleet does. Source priority matches
        ``evaluate``: the router's per-request histogram when present
        (hedging duplicates replica-side executions), the replica
        latency histogram otherwise. ``prev=None`` means "since boot"."""
        def _c(s, name):
            return ((s or {}).get("counters") or {}).get(name, 0)

        def _h(s, name):
            h = ((s or {}).get("histograms") or {}).get(name)
            return h.get("count", 0) if h else 0

        fleet_n = _h(cur, "fleet.request_latency_seconds")
        if fleet_n or _h(prev, "fleet.request_latency_seconds"):
            completed = fleet_n - _h(prev, "fleet.request_latency_seconds")
            misses = (_c(cur, "fleet.request_deadline_exceeded")
                      - _c(prev, "fleet.request_deadline_exceeded"))
        else:
            completed = (_h(cur, self.latency_metric)
                         - _h(prev, self.latency_metric))
            misses = (_c(cur, "serve.shed_deadline")
                      - _c(prev, "serve.shed_deadline"))
        completed = max(completed, 0)
        misses = max(misses, 0)
        denom = completed + misses
        attainment = 1.0 - (misses / denom) if denom else 1.0
        budget = 1.0 - self.deadline_target
        burn = ((1.0 - attainment) / budget) if budget else 0.0
        return {"completed": completed, "misses": misses,
                "attainment": round(attainment, 6),
                "burn": round(burn, 4)}

    # ------------------------------------------------------------------
    @staticmethod
    def render(report: dict) -> str:
        """The report as a terminal table (tools/fleet_report.py)."""
        lines = ["SLO report:"]
        order = ("requests_finished", "completed", "deadline_misses",
                 "deadline_attainment", "deadline_target",
                 "error_budget_burn", "p50_latency_ms", "p99_latency_ms",
                 "shed_rate", "breaker_trips", "breaker_open_seconds",
                 "ready_replicas", "execute_errors",
                 "failovers", "hedges", "hedge_win_rate",
                 "stale_version_rejected")
        for k in order:
            v = report.get(k)
            if v is None:
                continue
            lines.append(f"  {k:<26}{v}")
        for r, n in (report.get("shed_by_reason") or {}).items():
            lines.append(f"  {'shed[' + r + ']':<26}{n}")
        if report.get("breaches"):
            lines.append("  BREACHES:")
            for b in report["breaches"]:
                lines.append(f"    ! {b['detail']}")
        else:
            lines.append("  all SLO thresholds met")
        return "\n".join(lines)
