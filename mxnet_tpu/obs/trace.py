"""Span tracer — a low-overhead framework-level timeline.

The reference MXNet's ``src/profiler/`` hooks the dependency engine and dumps
a chrome-trace JSON of every op. Our engine is XLA, whose XPlane dump is
opaque above the HLO level — so this tracer records the *framework* phases
(data_wait / forward / backward / update / metric / checkpoint, RPCs,
checkpoint commits, chaos injections) and exports them as:

- **chrome-trace JSON** (``export_chrome_trace``): load the file in Perfetto
  (ui.perfetto.dev) or ``chrome://tracing`` — one track per thread, so the
  async checkpoint writer and prefetch workers show up beside the step loop;
- **JSONL event stream** (``stream_to``): one JSON object per line, appended
  and flushed as each span closes — survives SIGKILL mid-run (the chaos
  harness's process kills), tail -f-able on headless workers.

Overhead contract (tested in tests/test_obs.py):

- **Disabled** (the default): ``span()`` returns a shared no-op singleton —
  no event, no allocation retained, one module-flag check. The whole layer
  is gated on this ONE flag (``_ENABLED``), flipped by ``obs.enable()`` /
  ``MXNET_OBS=1``.
- **Enabled**: ``__enter__``/``__exit__`` cost two ``time.monotonic()``
  calls and one deque append into a bounded ring buffer (old events drop,
  newest win — a long run cannot OOM the tracer).

Spans nest per thread (a thread-local stack records depth); the context
manager is reentrant across threads because each thread owns its stack.

Distributed tracing (obs/context.py): when a :class:`~.context.TraceContext`
is active on the thread, each span allocates its own ``span_id``, records
``trace_id``/``span_id``/``parent_id`` in its attrs, and re-activates itself
as the current context for its body — so spans on the far side of an RPC
become children of the exact span that sent it. An active-but-UNSAMPLED
context short-circuits ``span()`` to the shared no-op (head-based sampling:
the whole trace is either recorded on every hop or costs one thread-local
read per span site).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import IO, List, Optional

from . import context as _context

__all__ = ["Tracer", "span", "event", "counter", "complete", "events",
           "reset", "drain", "stream_to", "to_chrome_trace",
           "export_chrome_trace", "tracer"]

# THE module flag: obs.enable()/disable() flip it; every instrumentation
# entry point checks it first. Plain module global — one LOAD_GLOBAL on the
# hot path, no function call.
_ENABLED = False

# routing sinks, installed by their owners (None = inactive):
# - _TAIL_SINK(trace_id, rec): obs/tail.py — spans of a tail-pending trace
#   go to the per-trace pending buffer instead of the durable ring; the
#   retention verdict at root close promotes them back through _record.
# - _BLACKBOX_SINK(rec, tracer): obs/blackbox.py — the flight recorder's
#   always-on ring sees EVERY event exactly once at creation time
#   (including tail-held ones that may later be dropped — the crash
#   bundle wants "what was this process doing", retained or not).
_TAIL_SINK = None
_BLACKBOX_SINK = None


def _trace_epoch() -> float:
    return time.monotonic()


class _NoopSpan:
    """Shared do-nothing context manager for disabled tracing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    """A live span: records (name, start, duration, thread, depth, attrs)
    on exit. Created only while tracing is enabled. When a (sampled)
    trace context is active, the span allocates a child span_id, runs its
    body AS the current context, and stamps trace/span/parent ids into its
    attrs — the cross-process parent chain."""

    __slots__ = ("_tracer", "name", "attrs", "t0", "_ctx", "_parent")

    def __init__(self, tracer: "Tracer", name: str, attrs: Optional[dict],
                 parent=None):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self._parent = parent
        self._ctx = None

    def __enter__(self):
        if self._parent is not None:
            self._ctx = self._parent.child()
            _context._set(self._ctx)
        self._tracer._stack().append(self)
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic()
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        else:  # unbalanced exit (generator teardown etc.) — drop to self
            while stack and stack[-1] is not self:
                stack.pop()
            if stack:
                stack.pop()
        if not stack:
            self._tracer._release_stack(stack)
        attrs = self.attrs
        if self._ctx is not None:
            _context._set(self._parent)
            attrs = dict(attrs) if attrs else {}
            attrs["trace_id"] = self._ctx.trace_id
            attrs["span_id"] = self._ctx.span_id
            attrs["parent_id"] = self._parent.span_id
        self._tracer._route(
            ("X", self.name, self.t0, t1 - self.t0,
             threading.get_ident(), len(stack), attrs), self._ctx)
        return False


class Tracer:
    """Bounded ring buffer of trace events + optional JSONL stream.

    Event records (tuples, cheapest to append):
      ("X", name, t_start, duration, tid, depth, attrs)   — completed span
      ("i", name, t,        None,    tid, depth, attrs)   — instant event
    Timestamps are ``time.monotonic()`` seconds; exporters rebase to the
    tracer's epoch so traces start near t=0.
    """

    def __init__(self, capacity: int = 65536):
        self.capacity = int(capacity)
        self._events: deque = deque(maxlen=self.capacity)
        self._local = threading.local()
        # tid -> the thread's live span stack (the same list object the
        # thread-local holds) — how the sampling profiler (obs/profile.py)
        # tags another thread's samples with its active span phase; a
        # cross-thread read of the last element is GIL-atomic (worst case
        # one sample period stale)
        self._thread_stacks: dict = {}
        # the two epochs are taken at the same instant: an event's unix
        # time is wall_epoch + ts — how multi-process traces merge onto
        # one timeline (obs/export.py, tools/trace_report.py)
        self._epoch = _trace_epoch()
        self._wall_epoch = time.time()
        self._stream: Optional[IO[str]] = None
        self._stream_path: Optional[str] = None
        self._stream_lock = threading.Lock()

    @property
    def stream_path(self) -> Optional[str]:
        """The JSONL path currently streamed to (None when not streaming)
        — lets a tool that must toggle telemetry restore the caller's
        stream afterwards (serve_bench.run_obs_overhead)."""
        return self._stream_path

    @property
    def wall_epoch(self) -> float:
        """Unix time of the tracer's t=0 (the cross-process clock anchor)."""
        return self._wall_epoch

    # -- hot path ----------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
            self._thread_stacks[threading.get_ident()] = st
        return st

    def _depth(self) -> int:
        """Current span depth WITHOUT registering a stack — instant
        events outside any span must not re-grow ``_thread_stacks``."""
        st = getattr(self._local, "stack", None)
        return len(st) if st else 0

    def _release_stack(self, stack: list) -> None:
        """Root closed on this thread: drop its ``_thread_stacks``
        registration. A serve plane spawning one handler thread per
        connection would otherwise grow the dict (and keep every dead
        thread's list alive) without bound; the next span on this thread
        re-registers a fresh list via ``_stack()``."""
        if getattr(self._local, "stack", None) is stack:
            self._local.stack = None
        self._thread_stacks.pop(threading.get_ident(), None)

    def thread_phases(self) -> dict:
        """``{tid: innermost active span name}`` across threads — the
        profiler's phase-attribution source."""
        out = {}
        for tid, st in list(self._thread_stacks.items()):
            try:
                out[tid] = st[-1].name
            except IndexError:
                pass  # the owner popped its last span mid-read
        return out

    def _route(self, rec: tuple, ctx) -> None:
        """One emit point for every completed event: feed the flight
        recorder (exactly once, at creation), then either hold the record
        in the tail-pending buffer (tail-flagged trace, verdict later) or
        record it durably. Promotion re-enters through ``_record`` so the
        blackbox never sees a promoted record twice."""
        bb = _BLACKBOX_SINK
        if bb is not None:
            bb(rec, self)
        if (ctx is not None and ctx.tail and not ctx.force
                and not ctx.sampled):
            sink = _TAIL_SINK
            if sink is not None:
                sink(ctx.trace_id, rec)
            # else drop: the tail bit arrived over the wire but THIS
            # process never enabled tail mode — it has no pending buffer
            # to hold the span and no verdict will ever promote it.
            # Recording durably here would bypass this process's own
            # head-sampling rate (a tail-mode client must not turn a
            # sample-0.05 replica into record-everything)
        else:
            self._record(rec)

    def _record(self, rec: tuple) -> None:
        self._events.append(rec)  # deque.append is atomic under the GIL
        stream = self._stream
        if stream is not None:
            line = json.dumps(self._event_dict(rec), default=str)
            with self._stream_lock:
                if self._stream is not None:
                    try:
                        self._stream.write(line + "\n")
                        self._stream.flush()  # survive SIGKILL mid-run
                    except (OSError, ValueError):
                        self._stream = None  # never fail training over a log

    def span(self, name: str, **attrs) -> "_Span | _NoopSpan":
        if not _ENABLED:
            return _NOOP
        ctx = _context.current()
        if ctx is not None and not ctx.records:
            return _NOOP  # head-based sampling: whole trace or nothing
        return _Span(self, name, attrs or None, parent=ctx)

    def event(self, name: str, **attrs) -> None:
        """Record an instant (zero-duration) event — chaos injections,
        preemption signals, retries. Carries the active trace context's
        ids so a tagged event lands inside its request's trace."""
        if not _ENABLED:
            return
        ctx = _context.current()
        if ctx is not None:
            if not ctx.records:
                return
            attrs = dict(attrs)
            attrs["trace_id"] = ctx.trace_id
            attrs["parent_id"] = ctx.span_id
        self._route(("i", name, time.monotonic(), None,
                     threading.get_ident(), self._depth(),
                     attrs or None), ctx)

    def counter(self, name: str, value: float) -> None:
        """Record one sample of a counter track (a Perfetto counter lane —
        ``device.live_bytes`` is the memory lane). Exported as a chrome
        ``"C"`` event; ``tools/trace_report.py`` renders the series."""
        if not _ENABLED:
            return
        self._route(("C", name, time.monotonic(), None,
                     threading.get_ident(), 0, {"value": float(value)}),
                    None)

    def complete(self, name: str, t_start: float, duration: float,
                 ctx=None, **attrs) -> None:
        """Record an already-measured span with an explicit start and
        duration (``time.monotonic()`` seconds) — for phases whose
        endpoints live on different threads, e.g. a serve request's
        queue_wait measured between the submitter's enqueue and the
        batcher's dispatch. ``ctx`` pins the span to a trace context
        captured on another thread (the batcher passes the request's)."""
        if not _ENABLED:
            return
        if ctx is None:
            ctx = _context.current()
        if ctx is not None:
            if not ctx.records:
                return
            attrs = dict(attrs)
            attrs["trace_id"] = ctx.trace_id
            attrs["span_id"] = _context.new_span_id()
            attrs["parent_id"] = ctx.span_id
        self._route(("X", name, t_start, max(duration, 0.0),
                     threading.get_ident(), self._depth(),
                     attrs or None), ctx)

    # -- introspection / export -------------------------------------------
    def events(self) -> List[tuple]:
        return list(self._events)

    def drain(self) -> List[dict]:
        """Atomically remove and return every buffered event as a list of
        normalized dicts (the JSONL/event schema). The telemetry plane's
        pull primitive: repeated ``OP_TELEMETRY`` collections each see only
        what happened since the last one, and a bounded ring drained
        periodically loses nothing."""
        out = []
        events = self._events
        while True:
            try:
                out.append(events.popleft())  # atomic under the GIL
            except IndexError:
                break
        return [self._event_dict(rec) for rec in out]

    def reset(self) -> None:
        self._events.clear()
        self._epoch = _trace_epoch()
        self._wall_epoch = time.time()
        # an attached stream's first clock record anchored the OLD epoch;
        # events after this reset are relative to the new one — append a
        # fresh anchor or every post-reset event would be rebased wrong
        # in a merged timeline (readers take the last clock record)
        with self._stream_lock:
            if self._stream is not None:
                try:
                    self._stream.write(json.dumps(
                        {"ph": "M", "name": "clock", "pid": os.getpid(),
                         "wall_epoch": self._wall_epoch}) + "\n")
                    self._stream.flush()
                except (OSError, ValueError):
                    self._stream = None

    def stream_to(self, path: Optional[str]) -> None:
        """Append completed events to ``path`` as JSONL (None closes)."""
        with self._stream_lock:
            if self._stream is not None:
                try:
                    self._stream.close()
                except OSError:
                    pass
                self._stream = None
            self._stream_path = path
            if path is not None:
                self._stream = open(path, "a", buffering=1)
                # clock anchor first: readers (trace_report/fleet_report)
                # rebase this file's events onto unix time with it, so
                # per-replica JSONL streams merge onto one timeline even
                # when the writer was SIGKILLed mid-run
                try:
                    self._stream.write(json.dumps(
                        {"ph": "M", "name": "clock", "pid": os.getpid(),
                         "wall_epoch": self._wall_epoch}) + "\n")
                    self._stream.flush()
                except (OSError, ValueError):
                    self._stream = None

    def stream_metrics(self, snapshot: dict) -> None:
        """Append a metrics-snapshot record to the JSONL stream (written by
        ``obs.disable()`` so a finished headless run's stream carries its
        final metrics table; tools/trace_report.py reads it back)."""
        with self._stream_lock:
            if self._stream is not None:
                try:
                    self._stream.write(json.dumps(
                        {"ph": "M", "name": "metrics",
                         "metrics": snapshot}, default=float) + "\n")
                    self._stream.flush()
                except (OSError, ValueError):
                    self._stream = None

    def _event_dict(self, rec: tuple) -> dict:
        ph, name, ts, dur, tid, depth, attrs = rec
        d = {"ph": ph, "name": name, "ts": ts - self._epoch, "tid": tid,
             "depth": depth, "pid": os.getpid()}
        if dur is not None:
            d["dur"] = dur
        if attrs:
            d["args"] = attrs
        return d

    def to_chrome_trace(self, metrics: Optional[dict] = None) -> dict:
        """Chrome Trace Event Format dict (Perfetto/about:tracing loadable).

        Durations use "X" complete events; instants use "i". A metrics
        snapshot rides along in ``otherData`` so one file carries the whole
        observability state (tools/trace_report.py reads it back).
        """
        pid = os.getpid()
        trace_events = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "mxnet_tpu"},
        }]
        tids = {}
        for rec in list(self._events):
            ph, name, ts, dur, tid, depth, attrs = rec
            tids.setdefault(tid, len(tids))
            ev = {"name": name, "ph": ph, "pid": pid, "tid": tid,
                  "ts": (ts - self._epoch) * 1e6}
            if ph == "X":
                ev["dur"] = (dur or 0.0) * 1e6
            elif ph == "i":
                ev["s"] = "t"  # thread-scoped instant
            # "C" counter samples carry only their args series
            if attrs:
                ev["args"] = dict(attrs)
            trace_events.append(ev)
        for tid, idx in tids.items():
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": f"thread-{idx}"
                         if idx else "main"}})
        out = {"traceEvents": trace_events, "displayTimeUnit": "ms",
               "otherData": {"pid": pid, "wall_epoch": self._wall_epoch}}
        if metrics is not None:
            out["otherData"]["metrics"] = metrics
        return out

    def export_chrome_trace(self, path: str,
                            metrics: Optional[dict] = None) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(metrics), f, default=str)
        return path


# the process-global tracer; module-level helpers delegate here
tracer = Tracer(capacity=int(os.environ.get("MXNET_OBS_BUFFER", "65536")))


def span(name: str, **attrs):
    """``with obs.trace.span("forward", epoch=3): ...`` — no-op singleton
    when tracing is disabled OR when the active trace context neither
    samples (head-based) nor tail-pends (obs/tail.py)."""
    if not _ENABLED:
        return _NOOP
    ctx = _context.current()
    if ctx is not None and not ctx.records:
        return _NOOP
    return _Span(tracer, name, attrs or None, parent=ctx)


def event(name: str, **attrs) -> None:
    if _ENABLED:
        tracer.event(name, **attrs)


def counter(name: str, value: float) -> None:
    """Module-level passthrough to :meth:`Tracer.counter`."""
    if _ENABLED:
        tracer.counter(name, value)


def complete(name: str, t_start: float, duration: float, ctx=None,
             **attrs) -> None:
    """Module-level passthrough to :meth:`Tracer.complete`."""
    if _ENABLED:
        tracer.complete(name, t_start, duration, ctx=ctx, **attrs)


def events() -> List[tuple]:
    return tracer.events()


def drain() -> List[dict]:
    return tracer.drain()


def reset() -> None:
    tracer.reset()


def stream_to(path: Optional[str]) -> None:
    tracer.stream_to(path)


def to_chrome_trace(metrics: Optional[dict] = None) -> dict:
    return tracer.to_chrome_trace(metrics)


def export_chrome_trace(path: str, metrics: Optional[dict] = None) -> str:
    return tracer.export_chrome_trace(path, metrics)
