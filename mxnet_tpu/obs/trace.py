"""Span tracer — a low-overhead framework-level timeline.

The reference MXNet's ``src/profiler/`` hooks the dependency engine and dumps
a chrome-trace JSON of every op. Our engine is XLA, whose XPlane dump is
opaque above the HLO level — so this tracer records the *framework* phases
(data_wait / forward / backward / update / metric / checkpoint, RPCs,
checkpoint commits, chaos injections) and exports them as:

- **chrome-trace JSON** (``export_chrome_trace``): load the file in Perfetto
  (ui.perfetto.dev) or ``chrome://tracing`` — one track per thread, so the
  async checkpoint writer and prefetch workers show up beside the step loop;
- **JSONL event stream** (``stream_to``): one JSON object per line, appended
  and flushed as each span closes — survives SIGKILL mid-run (the chaos
  harness's process kills), tail -f-able on headless workers.

Overhead contract (tested in tests/test_obs.py):

- **Disabled** (the default): ``span()`` returns a shared no-op singleton —
  no event, no allocation retained, one module-flag check. The whole layer
  is gated on this ONE flag (``_ENABLED``), flipped by ``obs.enable()`` /
  ``MXNET_OBS=1``.
- **Enabled**: ``__enter__``/``__exit__`` cost two ``time.monotonic()``
  calls and one deque append into a bounded ring buffer (old events drop,
  newest win — a long run cannot OOM the tracer).

Spans nest per thread (a thread-local stack records depth); the context
manager is reentrant across threads because each thread owns its stack.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import IO, List, Optional

__all__ = ["Tracer", "span", "event", "complete", "events", "reset",
           "stream_to", "to_chrome_trace", "export_chrome_trace", "tracer"]

# THE module flag: obs.enable()/disable() flip it; every instrumentation
# entry point checks it first. Plain module global — one LOAD_GLOBAL on the
# hot path, no function call.
_ENABLED = False


def _trace_epoch() -> float:
    return time.monotonic()


class _NoopSpan:
    """Shared do-nothing context manager for disabled tracing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    """A live span: records (name, start, duration, thread, depth, attrs)
    on exit. Created only while tracing is enabled."""

    __slots__ = ("_tracer", "name", "attrs", "t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._tracer._stack().append(self)
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        t1 = time.monotonic()
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        else:  # unbalanced exit (generator teardown etc.) — drop to self
            while stack and stack[-1] is not self:
                stack.pop()
            if stack:
                stack.pop()
        self._tracer._record(
            ("X", self.name, self.t0, t1 - self.t0,
             threading.get_ident(), len(stack), self.attrs))
        return False


class Tracer:
    """Bounded ring buffer of trace events + optional JSONL stream.

    Event records (tuples, cheapest to append):
      ("X", name, t_start, duration, tid, depth, attrs)   — completed span
      ("i", name, t,        None,    tid, depth, attrs)   — instant event
    Timestamps are ``time.monotonic()`` seconds; exporters rebase to the
    tracer's epoch so traces start near t=0.
    """

    def __init__(self, capacity: int = 65536):
        self.capacity = int(capacity)
        self._events: deque = deque(maxlen=self.capacity)
        self._local = threading.local()
        self._epoch = _trace_epoch()
        self._stream: Optional[IO[str]] = None
        self._stream_lock = threading.Lock()

    # -- hot path ----------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _record(self, rec: tuple) -> None:
        self._events.append(rec)  # deque.append is atomic under the GIL
        stream = self._stream
        if stream is not None:
            line = json.dumps(self._event_dict(rec), default=str)
            with self._stream_lock:
                if self._stream is not None:
                    try:
                        self._stream.write(line + "\n")
                        self._stream.flush()  # survive SIGKILL mid-run
                    except (OSError, ValueError):
                        self._stream = None  # never fail training over a log

    def span(self, name: str, **attrs) -> "_Span | _NoopSpan":
        if not _ENABLED:
            return _NOOP
        return _Span(self, name, attrs or None)

    def event(self, name: str, **attrs) -> None:
        """Record an instant (zero-duration) event — chaos injections,
        preemption signals, retries."""
        if not _ENABLED:
            return
        self._record(("i", name, time.monotonic(), None,
                      threading.get_ident(), len(self._stack()),
                      attrs or None))

    def complete(self, name: str, t_start: float, duration: float,
                 **attrs) -> None:
        """Record an already-measured span with an explicit start and
        duration (``time.monotonic()`` seconds) — for phases whose
        endpoints live on different threads, e.g. a serve request's
        queue_wait measured between the submitter's enqueue and the
        batcher's dispatch."""
        if not _ENABLED:
            return
        self._record(("X", name, t_start, max(duration, 0.0),
                      threading.get_ident(), len(self._stack()),
                      attrs or None))

    # -- introspection / export -------------------------------------------
    def events(self) -> List[tuple]:
        return list(self._events)

    def reset(self) -> None:
        self._events.clear()
        self._epoch = _trace_epoch()

    def stream_to(self, path: Optional[str]) -> None:
        """Append completed events to ``path`` as JSONL (None closes)."""
        with self._stream_lock:
            if self._stream is not None:
                try:
                    self._stream.close()
                except OSError:
                    pass
                self._stream = None
            if path is not None:
                self._stream = open(path, "a", buffering=1)

    def stream_metrics(self, snapshot: dict) -> None:
        """Append a metrics-snapshot record to the JSONL stream (written by
        ``obs.disable()`` so a finished headless run's stream carries its
        final metrics table; tools/trace_report.py reads it back)."""
        with self._stream_lock:
            if self._stream is not None:
                try:
                    self._stream.write(json.dumps(
                        {"ph": "M", "name": "metrics",
                         "metrics": snapshot}, default=float) + "\n")
                    self._stream.flush()
                except (OSError, ValueError):
                    self._stream = None

    def _event_dict(self, rec: tuple) -> dict:
        ph, name, ts, dur, tid, depth, attrs = rec
        d = {"ph": ph, "name": name, "ts": ts - self._epoch, "tid": tid,
             "depth": depth}
        if dur is not None:
            d["dur"] = dur
        if attrs:
            d["args"] = attrs
        return d

    def to_chrome_trace(self, metrics: Optional[dict] = None) -> dict:
        """Chrome Trace Event Format dict (Perfetto/about:tracing loadable).

        Durations use "X" complete events; instants use "i". A metrics
        snapshot rides along in ``otherData`` so one file carries the whole
        observability state (tools/trace_report.py reads it back).
        """
        pid = os.getpid()
        trace_events = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "mxnet_tpu"},
        }]
        tids = {}
        for rec in list(self._events):
            ph, name, ts, dur, tid, depth, attrs = rec
            tids.setdefault(tid, len(tids))
            ev = {"name": name, "ph": ph, "pid": pid, "tid": tid,
                  "ts": (ts - self._epoch) * 1e6}
            if ph == "X":
                ev["dur"] = (dur or 0.0) * 1e6
            else:
                ev["s"] = "t"  # thread-scoped instant
            if attrs:
                ev["args"] = dict(attrs)
            trace_events.append(ev)
        for tid, idx in tids.items():
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": f"thread-{idx}"
                         if idx else "main"}})
        out = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
        if metrics is not None:
            out["otherData"] = {"metrics": metrics}
        return out

    def export_chrome_trace(self, path: str,
                            metrics: Optional[dict] = None) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(metrics), f, default=str)
        return path


# the process-global tracer; module-level helpers delegate here
tracer = Tracer(capacity=int(os.environ.get("MXNET_OBS_BUFFER", "65536")))


def span(name: str, **attrs):
    """``with obs.trace.span("forward", epoch=3): ...`` — no-op singleton
    when tracing is disabled."""
    if not _ENABLED:
        return _NOOP
    return _Span(tracer, name, attrs or None)


def event(name: str, **attrs) -> None:
    if _ENABLED:
        tracer.event(name, **attrs)


def complete(name: str, t_start: float, duration: float, **attrs) -> None:
    """Module-level passthrough to :meth:`Tracer.complete`."""
    if _ENABLED:
        tracer.complete(name, t_start, duration, **attrs)


def events() -> List[tuple]:
    return tracer.events()


def reset() -> None:
    tracer.reset()


def stream_to(path: Optional[str]) -> None:
    tracer.stream_to(path)


def to_chrome_trace(metrics: Optional[dict] = None) -> dict:
    return tracer.to_chrome_trace(metrics)


def export_chrome_trace(path: str, metrics: Optional[dict] = None) -> str:
    return tracer.export_chrome_trace(path, metrics)
