"""Tail-based trace retention — decide what to keep AFTER the request
finishes (docs/OBSERVABILITY.md "Tail sampling").

PR 7's head-based sampling decides at the trace ROOT, before anything is
known about the request: at ``MXNET_OBS_SAMPLE=0.1`` the p99 outlier or
the deadline-exceeded request you need to debug is 90% likely to have
recorded nothing. Tail mode inverts the decision:

- **every** request records its spans — but into a bounded per-trace
  *pending* buffer, not the durable ring/JSONL;
- when the root span closes, a :class:`RetentionPolicy` looks at what the
  request actually WAS (latency, error / shed / deadline / hedged /
  breaker outcome, explicit force-retain) and either **promotes** the
  whole trace into the durable ring + JSONL stream or drops it;
- "interesting" retention is bounded by a token bucket (an error storm
  must not become a telemetry storm) and a small uniform baseline keeps a
  trickle of healthy-request traces for comparison. Budget exhaustion
  never starves the baseline; force-retain bypasses the bucket entirely.

Cross-process (the serve plane): the tail-pending bit rides the existing
wire context (``obs/context.py`` flags bit 1), so the front and every
replica a request touches hold their spans pending under the same
trace_id. The root's verdict is formed from what rode the existing reply
path (the INFER reply's shed/deadline/error status IS the front's verdict
on the request) and is *distributed* on the telemetry plane: retained
trace ids ride the ``OP_TELEMETRY`` request (client → front → every
replica via the fleet fan-out), promoting the matching pending spans into
the collected part. Pending traces that never hear a verdict expire after
``MXNET_OBS_TAIL_HOLD_S`` and drop cleanly — a replica buffers briefly,
never forever. Force-retained traces (flags bit 2) skip the pending hop
and stream durably at once on every hop.

Everything here is O(1) per span and bounded: ``MXNET_OBS_TAIL_TRACES``
pending traces of ``MXNET_OBS_TAIL_SPANS`` spans each, oldest evicted
(counted) on overflow.

OpenMetrics exemplars ride along: each retained trace with a latency
verdict stamps itself as the exemplar of the latency-histogram bucket it
landed in, so a p99 bucket in the Prometheus exposition links straight to
a kept trace id.
"""
from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

from . import context as _context
from . import metrics as _metrics
from . import trace as _trace
from ._env import env_float as _env_float, env_int as _env_int

__all__ = ["RetentionPolicy", "TailBuffer", "enabled", "enable", "disable",
           "buffer", "hold", "finish_root", "finish_remote", "resolve",
           "retained_ids", "note", "take_notes", "forced",
           "exemplars_snapshot", "stats", "reset", "EXEMPLAR_HISTOGRAMS"]

# latency histograms that get trace-id exemplars from retained traces
EXEMPLAR_HISTOGRAMS = ("serve.latency_seconds",
                       "fleet.request_latency_seconds")

_OUTCOME_INTERESTING = ("error", "shed", "deadline")


class RetentionPolicy:
    """The keep-or-drop decision as a (nearly) pure function.

    ``decide(duration_s, outcome, flags, forced, now)`` returns
    ``(retain, reason)``. Determinism knobs for tests: pass ``now`` to
    drive the token bucket's clock and ``rng`` (a ``random.Random``) to
    pin the uniform baseline.

    Rules, in order:

    1. ``forced`` → retain ("forced"); never consumes budget;
    2. interesting — outcome in {error, shed, deadline}, a hedged /
       breaker / deadline_exceeded flag, or latency ≥ ``slow_ms`` —
       retains while the token bucket (``budget_per_s`` steady rate,
       ``burst`` cap) has tokens;
    3. the uniform ``baseline`` probability retains regardless (applies
       to fast requests AND to interesting ones past the budget — budget
       exhaustion degrades tail sampling to baseline sampling, never to
       zero);
    4. drop ("fast_path" below the bar, "budget" past it).
    """

    def __init__(self, slow_ms: Optional[float] = None,
                 budget_per_s: Optional[float] = None,
                 burst: Optional[float] = None,
                 baseline: Optional[float] = None,
                 rng: Optional[random.Random] = None):
        self.slow_ms = slow_ms if slow_ms is not None \
            else _env_float("MXNET_OBS_TAIL_SLOW_MS", 250.0)
        self.budget_per_s = budget_per_s if budget_per_s is not None \
            else _env_float("MXNET_OBS_TAIL_BUDGET", 20.0)
        self.burst = float(burst) if burst is not None \
            else max(2.0 * self.budget_per_s, 1.0)
        self.baseline = baseline if baseline is not None \
            else _env_float("MXNET_OBS_TAIL_BASELINE", 0.01)
        self._rng = rng or random.Random(
            int.from_bytes(os.urandom(8), "little"))
        self._tokens = self.burst
        self._refill_at: Optional[float] = None
        self._lock = threading.Lock()

    def _take_token(self, now: float) -> bool:
        with self._lock:
            if self._refill_at is None:
                self._refill_at = now
            self._tokens = min(
                self.burst,
                self._tokens + (now - self._refill_at) * self.budget_per_s)
            self._refill_at = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def decide(self, duration_s: float, outcome: str = "ok",
               flags: Sequence[str] = (), forced: bool = False,
               now: Optional[float] = None) -> Tuple[bool, str]:
        if forced:
            return True, "forced"
        now = time.monotonic() if now is None else now
        reason = None
        if outcome in _OUTCOME_INTERESTING:
            reason = outcome
        elif flags:
            reason = str(next(iter(flags)))
        elif duration_s * 1e3 >= self.slow_ms:
            reason = "slow"
        if reason is not None:
            if self._take_token(now):
                return True, reason
            # budget exhausted: fall through to the baseline — tail
            # sampling degrades to uniform sampling, never to nothing
            if self._rng.random() < self.baseline:
                return True, "baseline"
            return False, "budget"
        if self.baseline > 0.0 and self._rng.random() < self.baseline:
            return True, "baseline"
        return False, "fast_path"


class TailBuffer:
    """Bounded per-trace pending store + retained-verdict log.

    ``hold`` files a span record under its trace id; ``finish`` applies
    the policy at root close (promote or drop); ``resolve`` applies a
    verdict list arriving over the telemetry plane AND expires traces
    past their hold window. All promotion goes through the process-global
    tracer, so promoted spans land in the ring and any attached JSONL
    stream exactly like head-sampled ones.
    """

    def __init__(self, policy: Optional[RetentionPolicy] = None,
                 max_traces: Optional[int] = None,
                 max_spans: Optional[int] = None,
                 hold_s: Optional[float] = None):
        self.policy = policy or RetentionPolicy()
        self.max_traces = max_traces if max_traces is not None \
            else _env_int("MXNET_OBS_TAIL_TRACES", 512)
        self.max_spans = max_spans if max_spans is not None \
            else _env_int("MXNET_OBS_TAIL_SPANS", 256)
        self.hold_s = hold_s if hold_s is not None \
            else _env_float("MXNET_OBS_TAIL_HOLD_S", 20.0)
        # trace_id -> {"recs": [rec], "t0": monotonic}
        self._pending: "OrderedDict[str, dict]" = OrderedDict()
        # recent retained ids (the verdict log distributed over
        # OP_TELEMETRY) and an LRU of ids already settled either way, so
        # a late span / late verdict after expiry resolves cleanly.
        # The log must cover everything the policy can retain within one
        # hold window (budget*hold + burst): a smaller cap forgets
        # verdicts before the telemetry fan-out carries them, and the
        # replicas' held spans for RETAINED traces expire as drops.
        # Bounded above so a test's effectively-infinite budget stays sane
        log_n = int(min(65536.0, max(
            256.0, self.policy.budget_per_s * self.hold_s
            + self.policy.burst + 64.0)))
        self._retained_log: deque = deque(maxlen=log_n)
        self._settled: "OrderedDict[str, bool]" = OrderedDict()
        self._lock = threading.Lock()
        # counters kept unconditionally (STATS works with obs gating off)
        self.retained = 0
        self.dropped = 0
        self.expired = 0
        self.overflow = 0

    # -- span intake ----------------------------------------------------
    def hold(self, trace_id: str, rec: tuple) -> None:
        # lock-free fast path: dict get + list append are GIL-atomic, and
        # EVERY span of EVERY request comes through here under tail mode
        # — a contended lock at this site convoys the whole serve plane.
        # The race it admits (finish() settles the trace between the get
        # and the append → this span misses the promotion flush) is the
        # straggler-drop the verdict plane already tolerates everywhere
        ent = self._pending.get(trace_id)
        if ent is not None:
            recs = ent["recs"]
            if len(recs) < self.max_spans:
                recs.append(rec)
            return
        evicted = 0
        straggler_retained = False
        with self._lock:
            ent = self._pending.get(trace_id)
            if ent is None:
                settled = self._settled.get(trace_id)
                if settled is not None:
                    # verdict already landed (a straggler span racing the
                    # root close / buffer expiry): retained traces take
                    # the span durably, dropped ones drop it — cleanly.
                    # The durable record (ring + JSONL write) happens
                    # OUTSIDE the lock, like every other flush site — a
                    # slow stream write must not convoy hold()/resolve()
                    straggler_retained = settled
                    ent = None
                else:
                    while len(self._pending) >= self.max_traces:
                        self._pending.popitem(last=False)
                        self.overflow += 1
                        evicted += 1
                    ent = self._pending[trace_id] = {
                        "recs": [], "t0": time.monotonic()}
            if ent is not None and len(ent["recs"]) < self.max_spans:
                ent["recs"].append(rec)
        if straggler_retained:
            _trace.tracer._record(rec)
        if evicted:
            self._count("tail.overflow", evicted)

    # -- verdicts -------------------------------------------------------
    def _promote_locked(self, trace_id: str, ent: Optional[dict],
                        retain: bool) -> List[tuple]:
        """Settle one trace (caller holds the lock); returns the records
        to promote (flushed to the tracer OUTSIDE the lock)."""
        self._settled[trace_id] = retain
        while len(self._settled) > 4096:
            self._settled.popitem(last=False)
        if retain:
            self.retained += 1
            self._retained_log.append(trace_id)
            return ent["recs"] if ent else []
        self.dropped += 1
        return []

    def finish(self, trace_id: str, duration_s: float, outcome: str = "ok",
               flags: Sequence[str] = (), forced: bool = False
               ) -> Tuple[bool, str]:
        """Root-span close: apply the policy and settle the trace."""
        retain, reason = self.policy.decide(duration_s, outcome=outcome,
                                            flags=flags, forced=forced)
        with self._lock:
            ent = self._pending.pop(trace_id, None)
            recs = self._promote_locked(trace_id, ent, retain)
        for rec in recs:
            _trace.tracer._record(rec)
        self._count(f"tail.retained.{reason}" if retain
                    else f"tail.dropped.{reason}")
        if retain:
            _record_exemplar(trace_id, duration_s)
        return retain, reason

    def resolve(self, retained_ids: Sequence[str]) -> int:
        """A verdict list from the telemetry plane: promote every pending
        trace named in it, then expire everything past the hold window.
        Ids that already expired here resolve to a no-op (the verdict
        lost the race; the spans are gone — counted, never an error)."""
        promoted = 0
        flush: List[tuple] = []
        with self._lock:
            for tid in retained_ids:
                ent = self._pending.pop(tid, None)
                if ent is None:
                    continue
                flush.extend(self._promote_locked(tid, ent, True))
                promoted += 1
        for rec in flush:
            _trace.tracer._record(rec)
        if promoted:
            self._count("tail.resolved", promoted)
        self.expire()
        return promoted

    def expire(self, now: Optional[float] = None) -> int:
        """Drop pending traces older than the hold window (no verdict is
        a verdict: the root never promoted them)."""
        now = time.monotonic() if now is None else now
        dropped = 0
        with self._lock:
            while self._pending:
                tid, ent = next(iter(self._pending.items()))
                if now - ent["t0"] < self.hold_s:
                    break
                self._pending.popitem(last=False)
                self._promote_locked(tid, None, False)
                self.expired += 1
                dropped += 1
        if dropped:
            self._count("tail.dropped.expired", dropped)
        return dropped

    # -- views ----------------------------------------------------------
    def retained_ids(self) -> List[str]:
        with self._lock:
            return list(self._retained_log)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def stats(self) -> dict:
        with self._lock:
            return {"pending": len(self._pending),
                    "retained": self.retained, "dropped": self.dropped,
                    "expired": self.expired, "overflow": self.overflow,
                    "hold_s": self.hold_s, "max_traces": self.max_traces,
                    "max_spans": self.max_spans}

    def _count(self, name: str, n: int = 1) -> None:
        if _trace._ENABLED:
            _metrics.registry.counter(name).inc(n)


# ---------------------------------------------------------------------------
# module state — the process-global buffer + thread-local outcome notes
# ---------------------------------------------------------------------------

_buffer: Optional[TailBuffer] = None
_tls = threading.local()
# exemplars: {histogram_name: {bucket_upper_repr: {"trace_id", "value",
# "ts"}}} — the most recent retained trace per latency bucket
_exemplars: Dict[str, Dict[str, dict]] = {}
_ex_lock = threading.Lock()


def enabled() -> bool:
    return _buffer is not None


def buffer() -> Optional[TailBuffer]:
    return _buffer


def enable(policy: Optional[RetentionPolicy] = None, **buffer_kw
           ) -> TailBuffer:
    """Turn tail mode on: new trace roots carry the tail-pending bit,
    spans route into the pending buffer, and root closes apply the
    retention policy. Implies nothing about ``obs.enable()`` — tail mode
    only matters while telemetry records at all."""
    global _buffer
    _buffer = TailBuffer(policy=policy, **buffer_kw)
    _context.set_tail_mode(True)
    _trace._TAIL_SINK = _buffer.hold
    return _buffer


def disable() -> None:
    global _buffer
    _context.set_tail_mode(False)
    _trace._TAIL_SINK = None
    _buffer = None


def reset() -> None:
    """Fresh buffer (same config) + cleared exemplars/notes (tests)."""
    global _buffer
    if _buffer is not None:
        _buffer = TailBuffer(policy=_buffer.policy,
                             max_traces=_buffer.max_traces,
                             max_spans=_buffer.max_spans,
                             hold_s=_buffer.hold_s)
        _trace._TAIL_SINK = _buffer.hold
    with _ex_lock:
        _exemplars.clear()
    if getattr(_tls, "notes", None):
        _tls.notes = None


def hold(trace_id: str, rec: tuple) -> None:
    b = _buffer
    if b is not None:
        b.hold(trace_id, rec)


# -- thread-local outcome notes (set on the request's own thread between
# root open and root close: shed/deadline branches, hedge/breaker events)

def note(outcome: Optional[str] = None, **flags) -> None:
    """Annotate the current thread's in-flight root: an outcome
    ("error"/"shed"/"deadline") and/or boolean flags ("hedged",
    "breaker"). Read + cleared by :func:`finish_root`. No-op with tail
    mode off — a note written while nothing will ever consume it would
    sit in this thread's TLS and contaminate the first request after a
    later ``enable()``."""
    if _buffer is None:
        return
    n = getattr(_tls, "notes", None)
    if n is None:
        n = _tls.notes = {"outcome": None, "flags": set()}
    if outcome is not None:
        n["outcome"] = outcome
    for k, v in flags.items():
        if v:
            n["flags"].add(k)


def take_notes() -> Tuple[Optional[str], set]:
    n = getattr(_tls, "notes", None)
    _tls.notes = None
    if n is None:
        return None, set()
    return n["outcome"], n["flags"]


class forced:
    """``with obs.tail.forced(): client.infer(...)`` — roots born in the
    block carry the force-retain bit: recorded durably at once on every
    hop, no pending buffer, no budget (the "keep THIS one" escape hatch
    for repro runs)."""

    def __enter__(self):
        self._prev = _context.get_force_retain()
        _context.set_force_retain(True)
        return self

    def __exit__(self, *exc):
        _context.set_force_retain(self._prev)
        return False


def finish_root(ctx, duration_s: float, outcome: Optional[str] = None
                ) -> Optional[Tuple[bool, str]]:
    """Called where a tail-mode root was born, when it closes. Merges the
    explicit ``outcome`` with the thread-local notes and applies the
    policy. No-op (None) for non-tail contexts or with tail mode off."""
    noted_outcome, flags = take_notes()
    if ctx is None or not (getattr(ctx, "tail", False)
                           or getattr(ctx, "force", False)):
        return None
    b = _buffer
    if b is None:
        return None
    if getattr(ctx, "force", False):
        # already durably recorded span by span; log the verdict so the
        # telemetry plane distributes it to the other hops' buffers
        with b._lock:
            recs = b._promote_locked(ctx.trace_id, None, True)
        for rec in recs:  # pragma: no cover — force traces never pend
            _trace.tracer._record(rec)
        b._count("tail.retained.forced")
        _record_exemplar(ctx.trace_id, duration_s)
        return True, "forced"
    return b.finish(ctx.trace_id, duration_s,
                    outcome=outcome or noted_outcome or "ok",
                    flags=sorted(flags))


def finish_remote(ctx, duration_s: float) -> Optional[Tuple[bool, str]]:
    """A NON-root hop's request end (the serve front handling a
    client-rooted trace). The verdict belongs to the remote root — but
    the root never sees this hop's thread-local notes: a hedge or
    breaker trip happens at the router, *after* the reply status the
    root will decide from was already determined. Flags noted here make
    the trace interesting locally: apply the policy with them, and a
    retain settles THIS hop's pending spans durably and logs the verdict
    so the telemetry fan-out promotes the replicas' too (the root's own
    rpc span still follows the root's verdict — an unavoidable
    asymmetry without widening the reply frame). Outcome notes
    (shed/deadline/error) are NOT re-decided here: they rode the reply
    status to the root, whose verdict stays authoritative — deciding
    them twice would spend retention budget twice. Always clears the
    thread's notes (they must never leak into the next request)."""
    noted_outcome, flags = take_notes()
    b = _buffer
    if (b is None or ctx is None or not getattr(ctx, "tail", False)
            or getattr(ctx, "force", False) or not flags):
        return None
    retain, reason = b.policy.decide(duration_s, outcome="ok",
                                     flags=sorted(flags))
    if not retain:
        # leave the trace pending: the root's verdict (slow/error at the
        # client) may still promote it before the hold window closes
        return None
    with b._lock:
        ent = b._pending.pop(ctx.trace_id, None)
        recs = b._promote_locked(ctx.trace_id, ent, True)
    for rec in recs:
        _trace.tracer._record(rec)
    b._count(f"tail.retained.{reason}")
    _record_exemplar(ctx.trace_id, duration_s)
    return True, reason


def resolve(retained_ids: Sequence[str]) -> int:
    """Apply a verdict list arriving over the telemetry plane."""
    b = _buffer
    if b is None or not retained_ids:
        return 0
    return b.resolve(list(retained_ids))


def retained_ids() -> List[str]:
    b = _buffer
    return b.retained_ids() if b is not None else []


def stats() -> Optional[dict]:
    b = _buffer
    return b.stats() if b is not None else None


# ---------------------------------------------------------------------------
# OpenMetrics exemplars — retained trace ids pinned to latency buckets
# ---------------------------------------------------------------------------

def _record_exemplar(trace_id: str, duration_s: float) -> None:
    """Stamp ``trace_id`` as the exemplar of the bucket ``duration_s``
    lands in, for every configured latency histogram that exists in the
    registry — the exposition then links a p99 bucket straight to a kept
    tail trace."""
    if duration_s is None:
        return
    for name in EXEMPLAR_HISTOGRAMS:
        h = _metrics.registry.get(name)
        if h is None or not hasattr(h, "buckets"):
            continue
        le = "+Inf"
        for b in h.buckets:
            if duration_s <= b:
                le = repr(b)
                break
        with _ex_lock:
            _exemplars.setdefault(name, {})[le] = {
                "trace_id": trace_id, "value": round(float(duration_s), 6),
                "ts": time.time()}


def exemplars_snapshot() -> Dict[str, Dict[str, dict]]:
    """``{histogram_name: {le: {"trace_id", "value", "ts"}}}`` — shipped
    in the telemetry part, rendered by ``obs/export.py``."""
    with _ex_lock:
        return {name: dict(by_le) for name, by_le in _exemplars.items()}


def set_buffer(b: Optional[TailBuffer]) -> None:
    """Swap the process buffer (tests)."""
    global _buffer
    _buffer = b
    _trace._TAIL_SINK = b.hold if b is not None else None
    _context.set_tail_mode(b is not None)
