"""Shared env-knob parsing for the obs package — fail-soft by design: a
garbled value falls back to the default instead of taking telemetry (and
the process it watches) down at import. The knobs themselves are
documented in the runtime env registry (``mxnet_tpu.runtime.env_list``).
"""
from __future__ import annotations

import os


def env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    return int(env_float(name, default))
