"""Fleet telemetry export — Prometheus text exposition + multi-process
trace merging (docs/OBSERVABILITY.md "Fleet telemetry").

Two consumers, two formats, one source (the per-process telemetry *part*
produced by ``obs.telemetry_part()`` and pulled over ``OP_TELEMETRY``):

- :func:`to_prometheus` / :func:`render_prometheus` — the metrics registry
  snapshot as Prometheus text exposition (version 0.0.4): counters get a
  ``_total`` suffix, histograms unroll into cumulative ``_bucket{le=...}``
  series plus ``_sum``/``_count``. Every described family (the
  ``obs.metrics.describe``/``description`` registry) ships a ``# HELP``
  line before its ``# TYPE``. Labels (``pid``/``role``) distinguish
  fleet members, so one scrape of the FleetServer front covers every
  replica. HTTP-free by design: the text rides the existing STATS/
  TELEMETRY wire opcodes or lands in a file — point a node_exporter
  textfile collector or a pushgateway at it, no web server in-process.
  Latency-histogram buckets additionally carry **OpenMetrics exemplars**
  when the tail-retention plane supplies them (``obs/tail.py``): the
  trace_id of the most recent *retained* trace that landed in a bucket
  rides as ``# {trace_id="..."} value ts`` — a p99 bucket links straight
  to a kept tail trace. Exemplars are OpenMetrics-only syntax (a mid-line
  ``#`` is a whole-scrape parse error to a strict 0.0.4 parser): pass
  ``openmetrics=False`` for exemplar-free 0.0.4 output when the file
  feeds a node_exporter textfile collector or a pushgateway.
- :func:`merge_chrome_parts` — N parts (client, router front, replicas,
  plus JSONL evidence files of SIGKILLed processes) onto ONE chrome trace
  with a lane per pid. Each tracer's timestamps are relative to its own
  monotonic epoch; the part's ``wall_epoch`` (unix time of that epoch,
  captured at the same instant) rebases them onto shared unix time. On one
  host the wall clocks agree to well under a millisecond; across hosts the
  skew is NTP-bounded — callers surface the note, we record the anchors.

:func:`merge_metrics` folds many registry snapshots into one (counters and
histogram buckets sum, gauges sum — queue depths and ready-counts add
across replicas) for fleet-level SLO math (obs/slo.py).
"""
from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["to_prometheus", "render_prometheus", "merge_metrics",
           "merge_chrome_parts", "hist_quantile"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_VAL_RE = re.compile(r"([\\\n\"])")


def _metric_name(name: str, prefix: str = "mxnet") -> str:
    """``serve.latency_seconds`` → ``mxnet_serve_latency_seconds``."""
    n = _NAME_RE.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return f"{prefix}_{n}" if prefix else n


def _labels_str(labels: Optional[dict], extra: Optional[dict] = None) -> str:
    merged = dict(labels or {})
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{k}="{_LABEL_VAL_RE.sub(lambda m: chr(92) + m.group(1), str(v))}"'
        for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _fmt(v) -> str:
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


def _help_escape(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _exemplar_suffix(ex: Optional[dict]) -> str:
    """OpenMetrics exemplar: ``# {trace_id="..."} value ts`` appended to a
    bucket line. Empty string when no exemplar landed in the bucket."""
    if not ex or not ex.get("trace_id"):
        return ""
    out = f' # {{trace_id="{ex["trace_id"]}"}} {_fmt(float(ex.get("value", 0.0)))}'
    if ex.get("ts") is not None:
        out += f" {_fmt(round(float(ex['ts']), 3))}"
    return out


def _rebucket_exemplars(ex: Optional[dict], bounds: Sequence[float]) -> dict:
    """Re-key exemplars onto the RENDERED bucket ladder. A histogram
    snapshot omits empty buckets, and the exemplar's stamped bucket is
    often exactly such a bucket (a shed/deadline verdict retains the
    trace without observing its latency into the histogram) — so an
    exemplar keyed to an unrendered bound attaches to the first rendered
    bucket that still contains its value (``value <= le``, which is all
    OpenMetrics requires). Values past every rendered bound land on
    ``+Inf``; ties within a bucket keep the most recent stamp."""
    if not ex:
        return {}
    entries = []
    for e in ex.values():
        try:
            entries.append((float(e.get("value", 0.0)), e))
        except (TypeError, ValueError):
            continue
    entries.sort(key=lambda t: t[0])
    out: dict = {}
    idx = 0
    for b in bounds:
        best = None
        while idx < len(entries) and entries[idx][0] <= b:
            cand = entries[idx][1]
            if best is None or (cand.get("ts") or 0) >= (best.get("ts") or 0):
                best = cand
            idx += 1
        if best is not None:
            out[repr(b)] = best
    best = None
    for _, e in entries[idx:]:
        if best is None or (e.get("ts") or 0) >= (best.get("ts") or 0):
            best = e
    if best is not None:
        out["+Inf"] = best
    return out


def render_prometheus(labeled_snapshots: Sequence[tuple],
                      prefix: str = "mxnet",
                      openmetrics: bool = True) -> str:
    """Render N ``(labels, registry_snapshot[, exemplars])`` tuples as one
    exposition. ``# TYPE`` (and, for described families, ``# HELP``)
    headers are emitted once per metric family even when many fleet
    members report the same names (the format forbids repeats).
    ``exemplars`` is the ``obs.tail.exemplars_snapshot()`` schema —
    ``{histogram_name: {le_repr: {"trace_id", "value", "ts"}}}``.

    ``openmetrics=True`` (default) emits OpenMetrics: exemplar suffixes
    on bucket lines plus the required ``# EOF`` terminator. Exemplars are
    a mid-line ``#``, which classic text format 0.0.4 rejects as a parse
    error for the WHOLE scrape — pass ``openmetrics=False`` for strict
    0.0.4 output (no exemplars, no EOF) when the file feeds a
    node_exporter textfile collector or a pushgateway."""
    # family → (type, orig_name, [(labels, payload, exemplars), ...]);
    # insertion-ordered so the output is stable across collections
    families: Dict[str, tuple] = {}

    def add(name, mtype, labels, payload, ex=None):
        fam = _metric_name(name, prefix)
        ent = families.get(fam)
        if ent is None:
            ent = families[fam] = (mtype, name, [])
        ent[2].append((labels, payload, ex))

    for entry in labeled_snapshots:
        labels, snap = entry[0], entry[1]
        exemplars = entry[2] if len(entry) > 2 else None
        for name, v in (snap.get("counters") or {}).items():
            add(name, "counter", labels, v)
        for name, v in (snap.get("gauges") or {}).items():
            add(name, "gauge", labels, v)
        for name, h in (snap.get("histograms") or {}).items():
            add(name, "histogram", labels, h,
                (exemplars or {}).get(name) if openmetrics else None)

    try:
        from .metrics import description as _description
    except ImportError:  # pragma: no cover — parser-only environments
        def _description(_name):
            return None

    lines: List[str] = []
    for fam in sorted(families):
        mtype, orig_name, series = families[fam]
        help_text = _description(orig_name)
        if help_text:
            lines.append(f"# HELP {fam} {_help_escape(help_text)}")
        lines.append(f"# TYPE {fam} {mtype}")
        for labels, payload, ex in series:
            if mtype == "counter":
                lines.append(f"{fam}_total{_labels_str(labels)} "
                             f"{_fmt(payload)}")
            elif mtype == "gauge":
                lines.append(f"{fam}{_labels_str(labels)} {_fmt(payload)}")
            else:  # histogram: cumulative le-buckets + _sum + _count
                buckets = payload.get("buckets") or {}
                bounds = sorted(
                    (float(k) for k in buckets if k != "+Inf"))
                ex_by_le = _rebucket_exemplars(ex, bounds)
                running = 0
                for b in bounds:
                    running += buckets.get(repr(b), buckets.get(str(b), 0))
                    lines.append(
                        f"{fam}_bucket{_labels_str(labels, {'le': _fmt(b)})}"
                        f" {running}{_exemplar_suffix(ex_by_le.get(repr(b)))}")
                lines.append(
                    f"{fam}_bucket{_labels_str(labels, {'le': '+Inf'})}"
                    f" {payload.get('count', running)}"
                    f"{_exemplar_suffix(ex_by_le.get('+Inf'))}")
                lines.append(f"{fam}_sum{_labels_str(labels)} "
                             f"{_fmt(float(payload.get('sum', 0.0)))}")
                lines.append(f"{fam}_count{_labels_str(labels)} "
                             f"{payload.get('count', 0)}")
    if lines and openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + ("\n" if lines else "")


def to_prometheus(snapshot: dict, labels: Optional[dict] = None,
                  prefix: str = "mxnet",
                  exemplars: Optional[dict] = None,
                  openmetrics: bool = True) -> str:
    """One registry snapshot (``obs.metrics.snapshot()``) as Prometheus
    text exposition. ``exemplars`` (``obs.tail.exemplars_snapshot()``)
    pins retained-trace ids to latency buckets (OpenMetrics only — see
    :func:`render_prometheus`)."""
    return render_prometheus([(labels, snapshot, exemplars)], prefix=prefix,
                             openmetrics=openmetrics)


def parts_to_prometheus(parts: Sequence[dict], prefix: str = "mxnet",
                        openmetrics: bool = True) -> str:
    """Telemetry parts (``obs.telemetry_part()`` schema) → one exposition,
    each part labeled by pid (+role when present). A part's ``exemplars``
    (tail mode) ride onto its histogram bucket lines (OpenMetrics only —
    see :func:`render_prometheus`)."""
    labeled = []
    seen = set()
    for p in parts:
        pid = p.get("pid", "?")
        if pid in seen:
            continue  # same process, same registry (see merge_chrome_parts)
        seen.add(pid)
        labels = {"pid": str(pid)}
        if p.get("role"):
            labels["role"] = str(p["role"])
        labeled.append((labels, p.get("metrics") or {}, p.get("exemplars")))
    return render_prometheus(labeled, prefix=prefix, openmetrics=openmetrics)


# ---------------------------------------------------------------------------
# metrics merging (fleet-level SLO math)
# ---------------------------------------------------------------------------

def hist_quantile(hist: dict, q: float) -> float:
    """Bucket-resolution quantile of a histogram *snapshot* (the registry's
    schema: ``{"count", "sum", "min", "max", "buckets": {bound: n}}``) —
    the registry's own estimator, reimplemented over serialized data so it
    works on merged fleet snapshots."""
    count = hist.get("count", 0)
    if not count:
        return 0.0
    buckets = hist.get("buckets") or {}
    bounds = sorted((float(k) for k in buckets if k != "+Inf"))
    target = q * count
    running = 0
    for b in bounds:
        running += buckets.get(repr(b), buckets.get(str(b), 0))
        if running >= target:
            return b
    return float(hist.get("max", bounds[-1] if bounds else 0.0))


def merge_metrics(snapshots: Sequence[dict]) -> dict:
    """Fold registry snapshots from many processes into one: counters and
    histogram buckets/counts/sums add; gauges add too (queue depths, ready
    counts, and breaker open-times are extensive across replicas —
    last-write semantics would silently drop all but one member)."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue  # a torn JSONL tail can surface as a non-dict record
        for name, v in (snap.get("counters") or {}).items():
            out["counters"][name] = out["counters"].get(name, 0) + v
        for name, v in (snap.get("gauges") or {}).items():
            out["gauges"][name] = out["gauges"].get(name, 0.0) + v
        for name, h in (snap.get("histograms") or {}).items():
            m = out["histograms"].get(name)
            if m is None:
                m = out["histograms"][name] = {
                    "count": 0, "sum": 0.0, "min": math.inf,
                    "max": -math.inf, "buckets": {}}
            m["count"] += h.get("count", 0)
            m["sum"] += h.get("sum", 0.0)
            if h.get("count", 0):
                m["min"] = min(m["min"], h.get("min", math.inf))
                m["max"] = max(m["max"], h.get("max", -math.inf))
            for k, n in (h.get("buckets") or {}).items():
                m["buckets"][k] = m["buckets"].get(k, 0) + n
    for h in out["histograms"].values():
        if not h["count"]:
            h["min"] = h["max"] = 0.0
        h["avg"] = (h["sum"] / h["count"]) if h["count"] else 0.0
        h["p50"] = hist_quantile(h, 0.5)
        h["p99"] = hist_quantile(h, 0.99)
    # keep the snapshot schema stable (sorted names, like the registry's)
    for k in out:
        out[k] = dict(sorted(out[k].items()))
    return out


# ---------------------------------------------------------------------------
# trace merging (one timeline, a lane per pid)
# ---------------------------------------------------------------------------

def merge_chrome_parts(parts: Sequence[dict],
                       metrics: Optional[dict] = None) -> dict:
    """N telemetry parts → one chrome-trace document. Every part gets its
    own pid lane (process_name = its role, or ``pid N``); events are
    rebased onto a shared origin via each part's ``wall_epoch`` anchor.
    Parts with no anchor (a pre-context JSONL, say) sit at the shared
    origin and the caller should surface the clock-skew caveat."""
    anchors = [p["wall_epoch"] for p in parts
               if isinstance(p, dict) and p.get("wall_epoch") is not None]
    base = min(anchors) if anchors else 0.0
    trace_events: List[dict] = []
    merged_metrics = []
    metric_pids = set()
    skipped = 0  # torn/garbled records (a SIGKILL'd stream's final line)
    for p in parts:
        if not isinstance(p, dict):
            skipped += 1
            continue
        pid = p.get("pid", 0)
        off = ((p["wall_epoch"] - base)
               if p.get("wall_epoch") is not None else 0.0)
        name = p.get("role") or f"pid {pid}"
        trace_events.append({"name": "process_name", "ph": "M", "pid": pid,
                             "tid": 0, "args": {"name": str(name)}})
        tids = {}
        for ev in p.get("spans") or ():
            if not isinstance(ev, dict):
                skipped += 1  # torn final record — skip, never raise
                continue
            ph = ev.get("ph", "X")
            if ph not in ("X", "i", "C"):
                continue  # clock/metrics metadata records
            tid = ev.get("tid", 0)
            tids.setdefault(tid, len(tids))
            out = {"name": ev.get("name", "?"), "ph": ph, "pid": pid,
                   "tid": tid, "ts": (ev.get("ts", 0.0) + off) * 1e6}
            if ph == "X":
                out["dur"] = (ev.get("dur") or 0.0) * 1e6
            elif ph == "i":
                out["s"] = "t"
            # "C" counter samples (device.live_bytes lane) carry args only
            if ev.get("args"):
                out["args"] = dict(ev["args"])
            trace_events.append(out)
        for tid, idx in tids.items():
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": f"thread-{idx}" if idx else "main"}})
        # one registry per PROCESS: parts sharing a pid (an in-process
        # LocalReplica fleet) snapshot the same registry — merging each
        # copy would multiply every count
        if p.get("metrics") and pid not in metric_pids:
            metric_pids.add(pid)
            merged_metrics.append(p["metrics"])
    other = {"merged_from": [
        {"pid": p.get("pid"), "role": p.get("role"),
         "wall_epoch": p.get("wall_epoch")}
        for p in parts if isinstance(p, dict)]}
    other["metrics"] = metrics if metrics is not None \
        else merge_metrics(merged_metrics)
    if skipped:
        other["skipped_records"] = skipped
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": other}
