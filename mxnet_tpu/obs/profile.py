"""Continuous stack profiler — always-on, stdlib-only, phase-attributed
(docs/OBSERVABILITY.md "Continuous profiling").

The reference MXNet's ``profiler.cc`` timeline is how every perf claim in
its docs was made; its modern equivalent is *continuous* profiling: a
sampling thread that costs so little it stays on in production, so "what
was this process doing for the last N seconds" is always answerable —
including by the flight recorder (obs/blackbox.py), which folds the most
recent samples into every crash bundle.

Implementation: a daemon thread wakes at ``MXNET_OBS_PROF_HZ`` (default
67 — deliberately co-prime with common 10/50/100 Hz work periods so the
sampler does not alias onto them), walks ``sys._current_frames()``, and
aggregates each thread's stack as a semicolon-folded string tagged with
that thread's **active span phase** (the tracer's per-thread span stack —
``serve.execute``, ``update.fused``, ``data_wait``, ...). Exports:

- :meth:`SamplingProfiler.folded` — collapsed-stack text
  (``phase;frame;frame count`` — feed to flamegraph.pl / speedscope);
- :meth:`SamplingProfiler.chrome_events` — a per-thread profiler lane for
  the merged chrome trace (consecutive same-leaf samples coalesce into
  one span), rendered by ``tools/trace_report.py``;
- :meth:`SamplingProfiler.recent` — the raw last-N-seconds sample ring
  (the flight recorder's slice).

Overhead is measured, not assumed: ``tools/serve_bench.py
--prof-overhead`` / the bench.py ``prof_overhead`` leg run the serve
closed loop with the profiler (and tail buffering) off vs on and gate the
delta under 5%.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter, deque
from typing import Dict, List, Optional

from . import metrics as _metrics
from . import trace as _trace
from ._env import env_float as _env_float

__all__ = ["SamplingProfiler", "start", "stop", "profiler", "enabled",
           "folded", "chrome_events", "recent"]


class SamplingProfiler:
    """Sample every thread's python stack at ``hz``, phase-tagged.

    ``depth`` bounds the folded stack (innermost frames win); the sample
    ring holds ``max_samples`` ``(ts, tid, phase, leaf)`` tuples (oldest
    drop). Aggregation is a Counter keyed by ``(phase, folded_stack)`` —
    memory stays bounded by distinct stacks, not run length.
    """

    def __init__(self, hz: Optional[float] = None,
                 depth: Optional[int] = None,
                 max_samples: Optional[int] = None):
        self.hz = float(hz) if hz else _env_float("MXNET_OBS_PROF_HZ", 67.0)
        if self.hz <= 0:
            raise ValueError("profiler hz must be > 0")
        self.depth = int(depth) if depth \
            else int(_env_float("MXNET_OBS_PROF_DEPTH", 48))
        cap = int(max_samples) if max_samples \
            else int(_env_float("MXNET_OBS_PROF_BUFFER", 65536))
        self._samples: deque = deque(maxlen=cap)
        self._folded: "Counter[tuple]" = Counter()
        # code-object-chain -> (folded string, leaf): string work happens
        # once per distinct stack, not once per sample (keys keep their
        # code objects alive — bounded by the program's code, fine)
        self._fold_cache: Dict[tuple, tuple] = {}
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_taken = 0
        self.ticks = 0
        self.started_at: Optional[float] = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "SamplingProfiler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop_evt.clear()
        self.started_at = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxtpu-obs-profiler")
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # one tick can outlive the timeout only if something holds
                # the GIL that long; the daemon thread exits on its next
                # wait() check — count the leak, don't hide it
                _metrics.registry.counter("prof.sampler_leaked").inc()
            self._thread = None

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- the sampling loop ----------------------------------------------
    @staticmethod
    def _fold(frame, depth: int) -> str:
        """Innermost-last semicolon fold: ``mod.fn;mod.fn;...``."""
        parts: List[str] = []
        f = frame
        while f is not None and len(parts) < depth:
            code = f.f_code
            mod = code.co_filename.rsplit(os.sep, 1)[-1]
            parts.append(f"{mod}:{code.co_name}")
            f = f.f_back
        parts.reverse()
        return ";".join(parts)

    def sample_once(self) -> int:
        """One sweep over every live thread (callable from tests without
        the thread). Returns the number of thread samples taken."""
        me = threading.get_ident()
        prof_tid = self._thread.ident if self._thread is not None else me
        phases = _trace.tracer.thread_phases()
        now = time.monotonic()
        taken = 0
        depth = self.depth
        cache = self._fold_cache
        frames = sys._current_frames()
        try:
            for tid, frame in frames.items():
                if tid == me or tid == prof_tid:
                    continue  # never profile the profiler
                phase = phases.get(tid, "idle")
                # every tick holds the GIL away from the threads being
                # profiled, so the per-sample work must stay tiny: walk
                # the code-object chain (attribute reads only) and fold
                # to strings once per DISTINCT stack — a serve loop shows
                # a few dozen distinct stacks across millions of ticks
                chain: List = []
                f = frame
                while f is not None and len(chain) < depth:
                    chain.append(f.f_code)
                    f = f.f_back
                key = tuple(chain)
                ent = cache.get(key)
                if ent is None:
                    stack = self._fold(frame, depth)
                    leaf = stack.rsplit(";", 1)[-1] if stack else "?"
                    ent = cache[key] = (stack, leaf)
                stack, leaf = ent
                with self._lock:
                    self._folded[(phase, stack)] += 1
                    self._samples.append((now, tid, phase, leaf))
                taken += 1
        finally:
            del frames  # frame objects pin their locals — drop promptly
        self.samples_taken += taken
        self.ticks += 1
        return taken

    def _loop(self) -> None:
        period = 1.0 / self.hz
        next_t = time.monotonic() + period
        while not self._stop_evt.wait(max(next_t - time.monotonic(), 0.0)):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — a profiler must never crash
                pass           # the process it watches
            next_t += period
            if next_t < time.monotonic() - 1.0:
                next_t = time.monotonic() + period  # fell behind: re-anchor

    # -- exports --------------------------------------------------------
    def folded(self, top: Optional[int] = None) -> str:
        """Collapsed-stack text: ``phase;frame;...;frame count`` per line
        (flamegraph.pl / speedscope input), hottest first."""
        with self._lock:
            items = self._folded.most_common(top)
        return "\n".join(f"{phase};{stack} {n}" if stack else f"{phase} {n}"
                         for (phase, stack), n in items)

    def phase_seconds(self) -> Dict[str, float]:
        """Approximate seconds spent per span phase (samples / hz)."""
        with self._lock:
            agg: Dict[str, float] = {}
            for (phase, _stack), n in self._folded.items():
                agg[phase] = agg.get(phase, 0.0) + n / self.hz
        return dict(sorted(agg.items(), key=lambda kv: -kv[1]))

    def recent(self, seconds: float = 10.0) -> List[dict]:
        """The last ``seconds`` of raw samples (the flight recorder's
        slice), ts rebased to the tracer's epoch so they merge with span
        timestamps."""
        cutoff = time.monotonic() - seconds
        epoch = _trace.tracer._epoch
        with self._lock:
            return [{"ts": ts - epoch, "tid": tid, "phase": phase,
                     "leaf": leaf}
                    for ts, tid, phase, leaf in self._samples
                    if ts >= cutoff]

    def chrome_events(self, seconds: Optional[float] = None) -> List[dict]:
        """The sample stream as a chrome-trace profiler lane: consecutive
        samples on one thread with the same (phase, leaf) coalesce into
        one ``X`` span named ``prof:<phase>`` (args carry the leaf frame).
        Normalized dicts (ts/dur in tracer-epoch seconds) — the schema
        ``trace_report.merge_loaded`` and telemetry parts speak."""
        period = 1.0 / self.hz
        cutoff = None if seconds is None else time.monotonic() - seconds
        epoch = _trace.tracer._epoch
        with self._lock:
            samples = [s for s in self._samples
                       if cutoff is None or s[0] >= cutoff]
        runs: Dict[int, list] = {}
        out: List[dict] = []

        def flush(tid):
            run = runs.pop(tid, None)
            if run is None:
                return
            t0, t_last, phase, leaf, n = run
            out.append({"ph": "X", "name": f"prof:{phase}",
                        "ts": t0 - epoch,
                        "dur": (t_last - t0) + period,
                        "tid": tid,
                        "args": {"leaf": leaf, "samples": n}})

        for ts, tid, phase, leaf, in samples:
            run = runs.get(tid)
            if (run is not None and run[2] == phase and run[3] == leaf
                    and ts - run[1] <= 2.5 * period):
                run[1] = ts
                run[4] += 1
            else:
                flush(tid)
                runs[tid] = [ts, ts, phase, leaf, 1]
        for tid in list(runs):
            flush(tid)
        out.sort(key=lambda e: e["ts"])
        return out

    def stats(self) -> dict:
        with self._lock:
            distinct = len(self._folded)
            buffered = len(self._samples)
        return {"hz": self.hz, "running": self.running(),
                "ticks": self.ticks, "samples": self.samples_taken,
                "distinct_stacks": distinct, "buffered": buffered}

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._folded.clear()
        self.samples_taken = 0
        self.ticks = 0


# ---------------------------------------------------------------------------
# module-level singleton
# ---------------------------------------------------------------------------

profiler: Optional[SamplingProfiler] = None


def enabled() -> bool:
    return profiler is not None and profiler.running()


def start(hz: Optional[float] = None) -> SamplingProfiler:
    """Start (or return) the process profiler at ``hz``
    (``MXNET_OBS_PROF_HZ``, default 67)."""
    global profiler
    if profiler is not None and profiler.running():
        return profiler
    profiler = SamplingProfiler(hz=hz)
    return profiler.start()


def stop() -> None:
    global profiler
    if profiler is not None:
        profiler.stop()


def folded(top: Optional[int] = None) -> str:
    return profiler.folded(top) if profiler is not None else ""


def chrome_events(seconds: Optional[float] = None) -> List[dict]:
    return profiler.chrome_events(seconds) if profiler is not None else []


def recent(seconds: float = 10.0) -> List[dict]:
    return profiler.recent(seconds) if profiler is not None else []
