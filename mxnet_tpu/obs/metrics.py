"""Metrics registry — named counters, gauges, and fixed-bucket histograms.

The reference framework's observability splits across ``mx.mon.Monitor``
(tensor stats), the profiler's aggregate tables, and ad-hoc logging; here
every numeric runtime signal lands in ONE process-global registry so a
single ``snapshot()`` / ``dump()`` shows dispatch counts, RPC latencies,
queue depths, and retrace churn side by side (docs/OBSERVABILITY.md has the
metric name catalog).

Design notes:

- **Names, not label sets.** Metrics are keyed by a flat dotted name
  (``kvstore.rpc.push_seq.seconds``); callers bake the one discriminating
  dimension into the name. This keeps ``observe()`` one dict lookup + one
  lock — cheap enough for per-RPC and per-dispatch call sites.
- **Fixed buckets.** Histograms use preset upper bounds (Prometheus-style
  latency ladder by default) so ``observe()`` is a bisect, never a resize,
  and snapshots are stable across runs.
- **Thread-safe.** Every mutation takes the metric's own lock: the async
  checkpoint writer, prefetch workers, and PS server handler threads all
  report concurrently.

The registry always exists and always works — the ``obs`` module flag only
gates whether *instrumentation call sites* feed it (obs/__init__.py).
``profiler.DispatchCounts`` is a delta view over this registry's
``dispatch.*`` counters, so the two systems cannot drift.
"""
from __future__ import annotations

import bisect
import json
import math
import threading
from typing import Dict, List, Optional, Sequence

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_BUCKETS", "registry", "counter", "gauge", "histogram",
           "snapshot", "dump", "reset", "remove", "describe", "description"]

# Prometheus-style latency ladder (seconds). Fine enough to separate a
# sub-ms fused dispatch from a 100ms RPC retry from a multi-second compile.
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Counter:
    """Monotonically increasing integer (retries, bytes, retraces)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def snapshot(self):
        return self._value

    def __repr__(self):
        return f"Counter({self.name}={self._value})"


class Gauge:
    """Last-write-wins float (queue depth, samples/sec, loss scale)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self):
        return self._value

    def __repr__(self):
        return f"Gauge({self.name}={self._value})"


class Histogram:
    """Fixed-bucket distribution (latencies, sizes).

    ``buckets`` are ascending upper bounds; an implicit +Inf bucket catches
    the overflow. ``quantile(q)`` gives a bucket-resolution estimate (good
    enough for a p50/p99 column in a report, not for SLO math).
    """

    __slots__ = ("name", "buckets", "_counts", "_count", "_sum", "_min",
                 "_max", "_lock")

    def __init__(self, name: str, buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += value
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Bucket-upper-bound estimate of the q-quantile (0 < q <= 1)."""
        with self._lock:
            total = self._count
            if total == 0:
                return 0.0
            target = q * total
            running = 0
            for i, c in enumerate(self._counts):
                running += c
                if running >= target:
                    return (self.buckets[i] if i < len(self.buckets)
                            else self._max)
            return self._max

    def snapshot(self) -> dict:
        with self._lock:
            count = self._count
            s = {"count": count, "sum": self._sum,
                 "min": self._min if count else 0.0,
                 "max": self._max if count else 0.0,
                 "avg": (self._sum / count) if count else 0.0,
                 "buckets": {("+Inf" if i == len(self.buckets)
                              else repr(self.buckets[i])): c
                             for i, c in enumerate(self._counts) if c}}
        s["p50"] = self.quantile(0.5)
        s["p99"] = self.quantile(0.99)
        return s

    def __repr__(self):
        return f"Histogram({self.name} count={self._count})"


class MetricsRegistry:
    """Process-global name → metric map with typed accessors.

    Accessors get-or-create: ``registry.counter("a.b").inc()`` is the whole
    instrumentation idiom. Requesting an existing name as a different type
    raises — silent type drift is how dashboards lie.
    """

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, *args):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(name, Histogram, buckets)

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def snapshot(self) -> dict:
        """Deep, stable snapshot: ``{"counters": {...}, "gauges": {...},
        "histograms": {...}}``, names sorted. Safe to mutate or serialize."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if isinstance(m, Counter):
                out["counters"][name] = m.snapshot()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.snapshot()
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def dump(self, fmt: str = "text") -> str:
        """Human table (fmt="text") or machine JSON (fmt="json")."""
        snap = self.snapshot()
        if fmt == "json":
            return json.dumps(snap, indent=2, default=float)
        if fmt != "text":
            raise ValueError(f"fmt must be 'text'|'json', got {fmt!r}")
        lines = []
        if snap["counters"]:
            lines.append(f"{'Counter':<44}{'Value':>14}")
            for n, v in snap["counters"].items():
                lines.append(f"{n:<44}{v:>14}")
        if snap["gauges"]:
            if lines:
                lines.append("")
            lines.append(f"{'Gauge':<44}{'Value':>14}")
            for n, v in snap["gauges"].items():
                lines.append(f"{n:<44}{v:>14.6g}")
        if snap["histograms"]:
            if lines:
                lines.append("")
            lines.append(f"{'Histogram':<44}{'Count':>8}{'Avg':>12}"
                         f"{'P50':>12}{'P99':>12}{'Max':>12}")
            for n, h in snap["histograms"].items():
                lines.append(f"{n:<44}{h['count']:>8}{h['avg']:>12.6g}"
                             f"{h['p50']:>12.6g}{h['p99']:>12.6g}"
                             f"{h['max']:>12.6g}")
        return "\n".join(lines) if lines else "(no metrics)"

    def remove(self, name: str) -> None:
        """Drop one metric by name (no-op when absent). The elastic serve
        plane uses this on scale-in: a removed replica's per-replica gauges
        (``fleet.replica<i>.*``) would otherwise sit in the Prometheus
        exposition forever as frozen last values."""
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        """Drop every metric (tests; a fresh run's registry is empty)."""
        with self._lock:
            self._metrics.clear()


# ---------------------------------------------------------------------------
# metric descriptions — the `# HELP` text of the Prometheus exposition
# ---------------------------------------------------------------------------
#
# Exact names first; dynamic families (``kvstore.rpc.<op>_seconds``) match
# by longest prefix. ``obs/export.py`` looks descriptions up per family
# when rendering, so a described metric ships its HELP line with every
# exposition and an undescribed one renders exactly as before.

_DESCRIPTIONS: Dict[str, str] = {
    # serve plane
    "serve.latency_seconds": "end-to-end INFER latency per executed request",
    "serve.queue_depth": "dynamic-batcher queue depth at last submit",
    "serve.batch_occupancy": "rows filled / bucket capacity of the last batch",
    "serve.requests": "INFER requests accepted by the batcher",
    "serve.shed_queue_full": "requests shed at the queue watermark",
    "serve.shed_deadline": "requests shed because their deadline passed",
    "serve.shed_draining": "requests shed during draining shutdown",
    "serve.reloads": "hot reloads committed by the serving engine",
    "serve.telemetry_errors": "OP_TELEMETRY handler failures",
    "serve.dump_errors": "OP_DUMP flight-recorder handler failures",
    "serve.batcher_thread_leaked": "batcher threads alive past close()",
    "serve.handler_threads_leaked": "connection handlers alive past stop()",
    # fleet plane
    "fleet.request_latency_seconds":
        "per-REQUEST latency at the router (hedges collapse to one)",
    "fleet.request_deadline_exceeded":
        "requests whose deadline passed before a reply",
    "fleet.requests": "requests routed by the fleet router",
    "fleet.failovers": "requests retried on another replica after a failure",
    "fleet.hedges": "tail-latency hedge duplicates launched",
    "fleet.hedge_wins": "hedged duplicates that answered first",
    "fleet.breaker_trips": "circuit-breaker open transitions",
    "fleet.breaker_open_seconds":
        "cumulative seconds any replica breaker spent not closed",
    "fleet.replicas_ready": "replicas passing readiness at last probe",
    "fleet.replicas_total": "replicas supervised by the pool",
    "fleet.generation": "fleet membership generation (bumps on every change)",
    "fleet.stale_version_rejected":
        "replies rejected for a stale engine version mid-reload",
    # kvstore / PS plane
    "kvstore.rpc.retries": "PS client RPC attempts after the first",
    "kvstore.rpc.failures": "PS client RPCs that exhausted the retry budget",
    "kvstore.bytes_pushed": "client payload bytes pushed to the PS",
    "kvstore.bytes_pulled": "client payload bytes pulled from the PS",
    "kvstore.server.bytes_received": "PS-server inbound payload bytes",
    "kvstore.server.threads_leaked": "PS handler threads alive past stop()",
    "kvstore.barrier_timeout": "barriers that timed out naming absent ranks",
    # health plane
    "health.loss": "sampled training loss (also a chrome counter track)",
    "health.loss_ewma": "EWMA of the sampled training loss",
    "health.grad_norm": "global gradient norm at the last sampled step",
    "health.update_ratio_max":
        "worst update-to-weight ratio across parameters",
    "health.nonfinite_grads":
        "non-finite gradient elements at the last sample",
    "health.nonfinite_total": "cumulative non-finite gradient elements",
    "health.scaler.skip_streak":
        "consecutive AMP-scaler skipped steps (the silent skip-loop signal)",
    "health.samples": "sentinel evaluations run",
    "health.rollbacks": "automatic checkpoint rollbacks taken",
    "health.lr_backoffs": "automatic learning-rate backoffs taken",
    "health.nan_provenance": "NaN blame passes run",
    # training-fleet telemetry plane (obs/fleetstats.py)
    "train.step.seconds": "per-rank optimizer-step wall time",
    "train.straggler.verdicts":
        "straggler verdicts fired by the fleet detector",
    "train.straggler.recoveries":
        "flagged ranks cleared after sustained recovery",
    "train.straggler.flagged": "ranks currently flagged as stragglers",
    "train.fleet.bad_parts":
        "piggybacked worker telemetry parts that failed to parse",
    "kvstore.generation": "PS membership generation (fleet view)",
    "kvstore.live_workers": "active workers at the last liveness sweep",
    "kvstore.server.push.apply_seconds":
        "optimizer-apply time per applied push (reduce-plane split)",
    "kvstore.server.push.wal_seconds":
        "WAL append+fsync time per applied push (reduce-plane split)",
    "kvstore.server.pull.serialize_seconds":
        "reply array encode+send time per pull (reduce-plane split)",
    "kvstore.telemetry_errors": "PS OP_TELEMETRY handler failures",
    "kvstore.stats_errors": "PS OP_STATS handler failures",
    # tail retention / profiler / flight recorder (the black-box plane)
    "tail.resolved":
        "pending traces promoted by a telemetry-plane verdict list",
    "tail.overflow": "pending traces evicted at the buffer cap",
    "blackbox.dumps": "flight-recorder bundles written",
    "blackbox.throttled": "automatic dumps suppressed by the cooldown",
}

# (prefix, help) families for dynamically named metrics — longest prefix
# wins so `kvstore.server.rpc.` beats `kvstore.rpc.` beats `kvstore.`
_FAMILY_DESCRIPTIONS = (
    ("train.step.", "per-rank step-phase durations (fleet accounting)"),
    ("train.straggler.rank",
     "1 while the named rank is flagged as a straggler"),
    ("kvstore.member", "per-member heartbeat age at the last liveness"
                       " sweep (removed when the member is pruned)"),
    ("kvstore.reduce_wait.",
     "per-rank wait at generation-scoped reduce release"),
    ("kvstore.reduce_last_arriver.",
     "rounds in which the named rank arrived last (what the fleet"
     " waited on)"),
    ("kvstore.barrier_wait.", "per-rank wait at barrier release"),
    ("kvstore.server.push.", "PS push service-time split (apply vs WAL)"),
    ("kvstore.server.pull.", "PS pull service-time split (serialize)"),
    ("kvstore.server.rpc.", "PS server-side service time per opcode"),
    ("kvstore.rpc.backoff", "per-retry backoff sleeps"),
    ("kvstore.rpc.", "PS client-side RPC latency per opcode"),
    ("serve.rpc.", "serve server-side service time per opcode"),
    ("serve.client.rpc", "serve client-side RPC latency"),
    ("serve.shed_", "requests shed, by reason"),
    ("fleet.replica", "per-replica supervisor view (queue depth, occupancy,"
                      " breaker state)"),
    ("health.breach.", "sentinel breaches per rule"),
    ("health.monitor.", "Monitor scalar stats routed through the health"
                        " plane"),
    ("tail.retained.", "tail-mode traces retained, by policy reason"),
    ("tail.dropped.", "tail-mode traces dropped, by policy reason"),
    ("dispatch.", "compiled-program executions, eager dispatches, and"
                  " host-device transfers"),
    ("device.", "XLA cost/memory accounting (docs/OBSERVABILITY.md"
                " 'Device plane')"),
    ("update.", "fused update engine compile/execute accounting"),
    ("io.prefetch.", "prefetching iterator queue telemetry"),
    ("checkpoint.", "checkpoint writer durations and backlog"),
    ("chaos.", "injected faults"),
    ("autoscale.", "SLO-driven autoscaler decisions"),
    ("tsan.", "runtime lock-order sanitizer findings"),
)


def describe(name: str, help_text: str, family: bool = False) -> None:
    """Register ``# HELP`` text for a metric name (or, with
    ``family=True``, a name prefix). Later registrations win."""
    global _FAMILY_DESCRIPTIONS
    if family:
        _FAMILY_DESCRIPTIONS = ((name, help_text),) + tuple(
            f for f in _FAMILY_DESCRIPTIONS if f[0] != name)
    else:
        _DESCRIPTIONS[name] = help_text


def description(name: str) -> Optional[str]:
    """The HELP text for a metric name: exact match first, then the
    longest matching family prefix; None when undescribed."""
    d = _DESCRIPTIONS.get(name)
    if d is not None:
        return d
    best = None
    for prefix, text in _FAMILY_DESCRIPTIONS:
        if name.startswith(prefix) and (best is None
                                        or len(prefix) > len(best[0])):
            best = (prefix, text)
    return best[1] if best else None


# the process-global default registry — module-level helpers delegate here
registry = MetricsRegistry()


def counter(name: str) -> Counter:
    return registry.counter(name)


def gauge(name: str) -> Gauge:
    return registry.gauge(name)


def histogram(name: str, buckets: Optional[Sequence[float]] = None) -> Histogram:
    return registry.histogram(name, buckets)


def snapshot() -> dict:
    return registry.snapshot()


def dump(fmt: str = "text") -> str:
    return registry.dump(fmt)


def remove(name: str) -> None:
    registry.remove(name)


def reset() -> None:
    registry.reset()
