"""Training-fleet telemetry plane — per-rank step attribution, straggler
blame, reduce-plane accounting (docs/OBSERVABILITY.md "Training-fleet
telemetry").

The serve plane has been fleet-observable since PR 7 (OP_TELEMETRY fan-out,
merged per-pid timelines); the *training* fleet was still rank-local: every
worker's step phases lived in its own ring buffer and "straggler" existed
only as barrier-timeout error text. This module closes that gap:

- :class:`StepAccounting` — windowed per-rank step-phase accounting. The
  fit loop's existing phase spans (``data_wait`` / ``forward`` /
  ``backward`` / ``elastic.sync_grads`` / ``update`` / ``metric`` /
  ``checkpoint``) are emitted through :func:`phase`, which wraps the
  ordinary ``obs.trace.span`` (same names, same timeline) *and* folds the
  durations into per-window summaries (``MXNET_OBS_FLEET_WINDOW`` steps
  per window) plus ``train.step.*`` histograms. Sealed windows ship to the
  PS server piggybacked on the worker's existing heartbeats — no new
  connection, no new RPC.
- :class:`StragglerDetector` — a PURE decision function over the fleet's
  windowed per-rank step times. Because elastic ``dist_sync`` is lockstep,
  a straggler drags *everyone's* step time up — raw step-time comparison
  sees nothing. The detector therefore compares each rank's **own time**
  (step time minus reduce-wait): the slow rank's own time lags while the
  fast ranks' inflation shows up as reduce-wait. A rank lagging the fleet
  median by ``factor`` for ``k`` consecutive windows is flagged, with the
  *phase blamed* (compute vs data-wait vs reduce-wait vs host) by largest
  excess over the fleet median. Hysteresis both ways: flagging needs ``k``
  lagging windows, clearing needs ``k`` windows below the (lower) recovery
  threshold — an oscillating rank cannot flap the verdict.
- :class:`FleetAggregator` — the PS-server side: caches each worker's
  piggybacked parts, aligns windows by index, runs the detector, surfaces
  verdicts as ``train.straggler.*`` metrics, obs events, a structured
  entry in the server's STATS, and ``on_straggler`` callbacks (the hook
  ROADMAP item 4's adaptive-lr / staleness policies will consume).
- :class:`HotKeyTable` — bounded top-N per-key reduce-plane accounting
  (pushes, bytes, apply time) using space-saving admission, so a
  million-key embedding table cannot grow the server's bookkeeping.
- :func:`collect` — one ``OP_TELEMETRY`` pull against a PS server returns
  the server's own telemetry part (its RPC lanes) plus every cached
  worker part: ``tools/train_report.py`` / ``tools/fleet_report.py --ps``
  merge the rank lanes into ONE chrome timeline via the existing
  wall-clock anchors; SIGKILL'd ranks contribute their JSONL corpses.

Everything is gated by the one ``MXNET_OBS`` discipline (zero-cost when
off; ``MXNET_OBS_FLEET=0`` vetoes just this plane) and the overhead is
measured, not assumed (``train_obs_overhead`` leg in bench.py, <5%).
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

from . import context as _context
from . import metrics as _metrics
from . import trace as _trace
from ._env import env_float, env_int

__all__ = ["StepAccounting", "StragglerDetector", "FleetAggregator",
           "HotKeyTable", "phase", "step_complete", "set_rank", "rank",
           "flush", "wire_part", "enabled", "categorize",
           "summarize_windows", "collect", "PHASE_BLAME",
           "BLAME_CATEGORIES", "reset"]

# span name -> blame category. Spans emitted by the fit loop keep their
# historical names (test_obs asserts them); the detector reasons in the
# four-category space the ISSUE names. Unknown phases fold into "host".
PHASE_BLAME = {
    "data_wait": "data_wait",
    "forward": "compute",
    "backward": "compute",
    "update": "compute",
    "elastic.sync_grads": "reduce_wait",
    "grad_sync": "reduce_wait",
    "kvstore.rpc": "reduce_wait",
    "metric": "host",
    "checkpoint": "host",
}
BLAME_CATEGORIES = ("data_wait", "compute", "reduce_wait", "host")


_VETO_CACHE = (None, False)  # (raw env string, parsed) — phase() is hot


def _fleet_veto() -> bool:
    global _VETO_CACHE
    raw = os.environ.get("MXNET_OBS_FLEET")
    if raw != _VETO_CACHE[0]:
        _VETO_CACHE = (raw, (raw or "").lower() in
                       ("0", "false", "no", "off"))
    return _VETO_CACHE[1]


def enabled() -> bool:
    """Fleet accounting records iff telemetry is on and not vetoed."""
    return _trace._ENABLED and not _fleet_veto()


def summarize_windows(wins) -> Optional[dict]:
    """Step-weighted per-rank summary over a window list: total steps,
    average step time, and the blame-category breakdown. ONE helper for
    the server's STATS and train_report's fallback path, so the report a
    dead server's ``--input`` doc renders can never diverge from the
    live STATS numbers. None when the windows carry no steps."""
    wins = list(wins or ())
    steps = sum(int(w.get("steps", 0)) for w in wins)
    if not steps:
        return None
    tsum = sum(float(w.get("step_time", 0.0)) * int(w.get("steps", 0))
               for w in wins)
    cats = {c: 0.0 for c in BLAME_CATEGORIES}
    for w in wins:
        c = categorize(w)
        for k in cats:
            cats[k] += c[k] * int(w.get("steps", 0))
    return {"windows": len(wins), "steps": steps,
            "step_time_avg": round(tsum / steps, 6),
            "phases": {k: round(v / steps, 6) for k, v in cats.items()}}


def categorize(window: dict) -> Dict[str, float]:
    """A window's per-step phase averages folded into the four blame
    categories; unaccounted step time (callbacks, health sampling, python
    overhead) lands in ``host``."""
    phases = window.get("phases") or {}
    cats = {c: 0.0 for c in BLAME_CATEGORIES}
    for name, v in phases.items():
        cats[PHASE_BLAME.get(name, "host")] += float(v)
    resid = float(window.get("step_time", 0.0)) - sum(
        float(v) for v in phases.values())
    cats["host"] += max(0.0, resid)
    return cats


# ---------------------------------------------------------------------------
# worker side: windowed per-rank step-phase accounting
# ---------------------------------------------------------------------------

class _PhaseCtx:
    """Wraps the ordinary obs span: same name on the timeline, duration
    additionally folded into the step accounting — and the chaos straggler
    injector's delay (``MXNET_CHAOS_SLOW``) fires INSIDE the span, so the
    injected lag is visible as the stretched phase it blames."""

    __slots__ = ("_acc", "_name", "_span", "_t0", "_chaos")

    def __init__(self, acc, name, span, chaos):
        self._acc = acc
        self._name = name
        self._span = span
        self._chaos = chaos

    def __enter__(self):
        self._span.__enter__()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        if self._chaos is not None:
            self._chaos.maybe_delay(self._name)
        if self._acc is not None:
            self._acc._add_phase(self._name,
                                 time.monotonic() - self._t0)
        return self._span.__exit__(*exc)


_CHAOS_SLOW = None  # resolved lazily: chaos imports obs at package import


def _chaos_slow_mod():
    global _CHAOS_SLOW
    if _CHAOS_SLOW is None:
        from ..chaos import slow as _slow

        _CHAOS_SLOW = _slow
    return _CHAOS_SLOW


class StepAccounting:
    """Per-rank windowed step-phase accumulator.

    One instance per rank: the module-level singleton backs the real fit
    loop; tests and in-process benches construct one per simulated rank
    (then ``own_spans=False`` keeps them from fighting over the process's
    one tracer ring / metrics registry).
    """

    def __init__(self, rank: Optional[int] = None,
                 window: Optional[int] = None, own_spans: bool = True,
                 ship_interval_s: Optional[float] = None):
        self._rank = rank
        self.window = int(window if window is not None
                          else env_int("MXNET_OBS_FLEET_WINDOW", 10))
        self.own_spans = own_spans
        self._ship_s = float(ship_interval_s if ship_interval_s is not None
                             else env_float("MXNET_OBS_FLEET_SHIP_S", 2.0))
        self._max_spans = env_int("MXNET_OBS_FLEET_MAX_SPANS", 4096)
        self._lock = threading.Lock()
        self._reset_state()

    def _reset_state(self):
        self._step_phases: Dict[str, float] = {}
        self._last_step_t: Optional[float] = None
        self._cur_idx: Optional[int] = None
        self._cur = None  # (steps, time_sum, {phase: sum})
        self.windows: deque = deque(maxlen=256)  # sealed, local history
        self._ship: deque = deque(maxlen=256)    # sealed, not yet shipped
        self._last_ship = 0.0
        self._hists: Dict[str, object] = {}  # phase-name -> Histogram

    # -- identity --------------------------------------------------------
    @property
    def rank(self) -> int:
        if self._rank is None:
            self._rank = int(os.environ.get(
                "DMLC_WORKER_ID", os.environ.get("MXNET_WORKER_ID", 0))
                or 0)
        return self._rank

    def set_rank(self, r: int) -> None:
        self._rank = int(r)

    # -- hot path --------------------------------------------------------
    def phase(self, name: str, **attrs):
        """Context manager: the ordinary ``obs.trace.span(name)`` plus
        step accounting (when this plane records) plus the deterministic
        straggler injector's delay point. One flag check each when all
        three are off."""
        mod = _chaos_slow_mod()
        chaos = mod if mod.enabled() else None
        acc = self if enabled() else None
        span = _trace.span(name, **attrs)
        if acc is None and chaos is None:
            return span
        return _PhaseCtx(acc, name, span, chaos)

    def _add_phase(self, name: str, dt: float) -> None:
        self._step_phases[name] = self._step_phases.get(name, 0.0) + dt

    def step_complete(self, step: int) -> None:
        """Close one optimizer step: fold its phases into the current
        window, sealing (and queueing for shipment) when ``step`` crosses
        a window boundary. Step time is wall time since the previous
        ``step_complete`` — callbacks and everything else between phases
        land in the ``host`` residual."""
        if not enabled():
            self._step_phases = {}
            self._last_step_t = None
            return
        now = time.monotonic()
        phases, self._step_phases = self._step_phases, {}
        if self._last_step_t is not None:
            step_time = now - self._last_step_t
        else:
            step_time = sum(phases.values())
        self._last_step_t = now
        idx = (int(step) - 1) // self.window if step > 0 else 0
        if self._cur_idx is None:
            self._cur_idx = idx
        if idx != self._cur_idx:
            self._seal()
            self._cur_idx = idx
        if self._cur is None:
            self._cur = [0, 0.0, {}]
        self._cur[0] += 1
        self._cur[1] += step_time
        for name, dt in phases.items():
            self._cur[2][name] = self._cur[2].get(name, 0.0) + dt
        # per-step histograms (the metric-catalog surface; windows are the
        # wire surface) — Histogram objects cached per name so the hot
        # path skips the registry lookup and the f-string
        hists = self._hists
        h = hists.get("")
        if h is None:
            h = hists[""] = _metrics.registry.histogram(
                "train.step.seconds")
        h.observe(step_time)
        for name, dt in phases.items():
            h = hists.get(name)
            if h is None:
                h = hists[name] = _metrics.registry.histogram(
                    f"train.step.{name}_seconds")
            h.observe(dt)

    def _seal(self) -> None:
        """Close the current window into the sealed/ship queues."""
        if self._cur is None or not self._cur[0]:
            self._cur = None
            return
        steps, total, phases = self._cur
        win = {"w": int(self._cur_idx or 0), "steps": steps,
               "step_time": total / steps,
               "phases": {k: v / steps for k, v in phases.items()},
               "t": time.time()}
        self._cur = None
        with self._lock:
            self.windows.append(win)
            self._ship.append(win)

    def flush(self) -> None:
        """Seal a partial window (end of fit / bench segment)."""
        self._seal()
        self._cur_idx = None
        self._last_step_t = None

    # -- shipping (called from the Heartbeater thread) -------------------
    def wire_part(self) -> Optional[bytes]:
        """The piggyback payload for the next heartbeat: sealed unshipped
        windows plus (for the rank's real accounting) the drained span
        ring, metrics snapshot, and clock anchor — i.e. this rank's
        telemetry part, shipped incrementally. Returns None when there is
        nothing new and the ship interval hasn't elapsed (the common
        heartbeat pays one lock + two compares)."""
        if not enabled():
            return None
        now = time.monotonic()
        with self._lock:
            has_windows = bool(self._ship)
            if not has_windows and now - self._last_ship < self._ship_s:
                return None
            wins = list(self._ship)
            self._ship.clear()
            self._last_ship = now
        part = {"rank": self.rank, "pid": os.getpid(),
                "wall_epoch": _trace.tracer.wall_epoch, "windows": wins}
        if self.own_spans:
            spans = _trace.tracer.drain()
            if len(spans) > self._max_spans:
                spans = spans[-self._max_spans:]
            part["spans"] = spans
            part["metrics"] = _metrics.snapshot()
        try:
            return json.dumps(part, default=float).encode("utf-8")
        except (TypeError, ValueError):
            return None


# the rank's real accounting — Module.fit and the elastic session use it
_ACC = StepAccounting()


def phase(name: str, **attrs):
    return _ACC.phase(name, **attrs)


def step_complete(step: int) -> None:
    _ACC.step_complete(step)


def set_rank(r: int) -> None:
    _ACC.set_rank(r)


def rank() -> int:
    return _ACC.rank


def flush() -> None:
    _ACC.flush()


def wire_part() -> Optional[bytes]:
    return _ACC.wire_part()


def reset() -> None:
    _ACC._reset_state()


# ---------------------------------------------------------------------------
# the pure decision function
# ---------------------------------------------------------------------------

def _median(vals: List[float]) -> float:
    return statistics.median(vals) if vals else 0.0


class StragglerDetector:
    """Flag a lagging rank and blame the phase — a pure function over
    windowed per-rank step summaries, no wire, no clock, no globals.

    Per window index, call :meth:`observe` with ``{rank: window}`` where a
    window is ``{"steps", "step_time", "phases": {span_name: s}}`` (the
    :class:`StepAccounting` schema). Returns the list of NEW verdicts:
    ``{"kind": "straggler"|"recovered", "rank", "window", "streak",
    "ratio", "blame", ...}``.

    Lag metric: *own time* (step time minus reduce-wait) against the
    median of the OTHER ranks' own time — under lockstep sync every rank's
    raw step time equals the slowest rank's, so raw comparison is blind;
    own time isolates each rank's contribution. A rank whose raw step
    time AND reduce-wait both lag the fleet (without its own time
    lagging) is flagged with ``blame="reduce_wait"`` — the async-mode
    shape where one rank's RPC path (not its compute) is slow.

    Hysteresis: flag at ``k`` consecutive lagging windows; clear only
    after ``k`` consecutive windows below the recovery threshold
    (``1 + (factor-1)/2``) — a rank oscillating around ``factor`` cannot
    flap the verdict.
    """

    def __init__(self, factor: Optional[float] = None,
                 k: Optional[int] = None, min_ranks: int = 2):
        self.factor = float(factor if factor is not None
                            else env_float("MXNET_OBS_FLEET_FACTOR", 1.5))
        self.k = int(k if k is not None
                     else env_int("MXNET_OBS_FLEET_K", 3))
        self.min_ranks = max(2, int(min_ranks))
        self.recover = 1.0 + (self.factor - 1.0) / 2.0
        self._streak: Dict[int, int] = {}
        self._clear_streak: Dict[int, int] = {}
        self._blames: Dict[int, Dict[str, int]] = {}
        self.flagged: Dict[int, dict] = {}  # rank -> live verdict

    def observe(self, index: int, per_rank: Dict[int, dict]) -> List[dict]:
        events: List[dict] = []
        usable = {r: w for r, w in per_rank.items()
                  if w and w.get("steps")}
        if len(usable) < self.min_ranks:
            return events
        cats = {r: categorize(w) for r, w in usable.items()}
        own = {r: max(1e-9, usable[r]["step_time"]
                      - cats[r]["reduce_wait"]) for r in usable}
        raw = {r: float(usable[r]["step_time"]) for r in usable}
        for r in sorted(usable):
            others = [o for o in usable if o != r]
            med_own = max(_median([own[o] for o in others]), 1e-9)
            ratio = own[r] / med_own
            lagging = ratio >= self.factor
            blame = None
            if lagging:
                med_cat = {c: _median([cats[o][c] for o in others])
                           for c in ("data_wait", "compute", "host")}
                excess = {c: cats[r][c] - med_cat[c]
                          for c in ("data_wait", "compute", "host")}
                blame = max(excess, key=lambda c: excess[c])
            else:
                raw_ratio = raw[r] / max(
                    _median([raw[o] for o in others]), 1e-9)
                red_ratio = cats[r]["reduce_wait"] / max(
                    _median([cats[o]["reduce_wait"] for o in others]),
                    1e-9)
                if raw_ratio >= self.factor and red_ratio >= self.factor:
                    lagging, ratio, blame = True, raw_ratio, "reduce_wait"
            if lagging:
                self._clear_streak[r] = 0
                self._streak[r] = self._streak.get(r, 0) + 1
                bl = self._blames.setdefault(r, {})
                bl[blame] = bl.get(blame, 0) + 1
                if r in self.flagged:
                    v = self.flagged[r]
                    v["windows"] = v.get("windows", 0) + 1
                    v["ratio"] = round(ratio, 3)
                elif self._streak[r] >= self.k:
                    verdict = {
                        "kind": "straggler", "rank": r, "window": index,
                        "streak": self._streak[r],
                        "ratio": round(ratio, 3),
                        "blame": max(bl, key=lambda c: bl[c]),
                        "step_time": round(raw[r], 6),
                        "own_time": round(own[r], 6),
                        "fleet_median_own": round(med_own, 6),
                        "phases": {c: round(cats[r][c], 6)
                                   for c in BLAME_CATEGORIES},
                        "windows": self._streak[r]}
                    self.flagged[r] = verdict
                    events.append(dict(verdict))
            else:
                self._streak[r] = 0
                if r in self.flagged:
                    if ratio < self.recover:
                        cs = self._clear_streak.get(r, 0) + 1
                        self._clear_streak[r] = cs
                        if cs >= self.k:
                            v = self.flagged.pop(r)
                            self._blames.pop(r, None)
                            self._clear_streak[r] = 0
                            events.append({
                                "kind": "recovered", "rank": r,
                                "window": index,
                                "ratio": round(ratio, 3),
                                "was_blamed": v.get("blame")})
                    else:
                        self._clear_streak[r] = 0  # between recover and
                        # factor: neither extends the lag streak nor
                        # counts toward clearing — the flap guard
                else:
                    self._blames.pop(r, None)
        return events


# ---------------------------------------------------------------------------
# reduce-plane accounting: bounded top-N hot keys (space-saving admission)
# ---------------------------------------------------------------------------

class HotKeyTable:
    """Bounded per-key push accounting. At capacity, a new key evicts the
    coldest entry and inherits its push count + 1 (the space-saving
    sketch), so genuinely hot keys can still surface after the table
    filled while ``len(table)`` never exceeds ``capacity``. Counts for
    late-admitted keys are therefore upper bounds — the table answers
    "which keys are hot", not exact ledgers."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = int(capacity if capacity is not None
                            else env_int("MXNET_OBS_FLEET_HOT_KEYS", 32))
        self._lock = threading.Lock()
        self._t: Dict[str, dict] = {}
        self._t0 = time.monotonic()

    def __len__(self) -> int:
        return len(self._t)

    def record(self, key: str, nbytes: int, apply_s: float = 0.0) -> None:
        with self._lock:
            e = self._t.get(key)
            if e is None:
                inherited = 0
                if len(self._t) >= self.capacity:
                    coldest = min(self._t, key=lambda k:
                                  self._t[k]["pushes"])
                    inherited = self._t.pop(coldest)["pushes"]
                e = self._t[key] = {"pushes": inherited, "bytes": 0,
                                    "apply_s": 0.0}
            e["pushes"] += 1
            e["bytes"] += int(nbytes)
            e["apply_s"] += float(apply_s)

    def snapshot(self, n: Optional[int] = None) -> List[dict]:
        """Top-N by push count, with rates over the table's lifetime."""
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        with self._lock:
            rows = [{"key": k, "pushes": e["pushes"], "bytes": e["bytes"],
                     "push_rate": round(e["pushes"] / elapsed, 3),
                     "apply_ms_avg": round(
                         e["apply_s"] / max(e["pushes"], 1) * 1e3, 3)}
                    for k, e in self._t.items()]
        rows.sort(key=lambda r: (-r["pushes"], r["key"]))
        return rows[:n] if n else rows


# ---------------------------------------------------------------------------
# server side: cache worker parts, run the detector, surface verdicts
# ---------------------------------------------------------------------------

def _sanitize_window(w) -> Optional[dict]:
    """A piggybacked window with coerced numerics, or None when garbage.
    Validation happens at INGEST so a version-skewed or buggy worker can
    neither poison the cache nor crash the detector later — ``add_part``'s
    contract is that telemetry never breaks a heartbeat."""
    try:
        out = {"w": int(w["w"]), "steps": int(w.get("steps", 0)),
               "step_time": float(w.get("step_time", 0.0)),
               "phases": {str(k): float(v)
                          for k, v in (w.get("phases") or {}).items()}}
        if "t" in w and w["t"] is not None:
            out["t"] = float(w["t"])
        return out
    except (KeyError, ValueError, TypeError, AttributeError):
        return None


class _MemberTelemetry:
    __slots__ = ("rank", "pid", "wall_epoch", "windows", "spans", "metrics",
                 "last_seen")

    def __init__(self):
        self.rank = None
        self.pid = None
        self.wall_epoch = None
        self.windows: "OrderedDict" = OrderedDict()  # idx -> window
        self.spans: List[dict] = []
        self.metrics: dict = {}
        self.last_seen = time.monotonic()


class FleetAggregator:
    """PS-server-side cache of per-worker telemetry parts + the straggler
    detector run over them. ``add_part`` is called from the heartbeat
    handler (the piggyback path); ``parts``/``stats`` answer OP_TELEMETRY
    and STATS."""

    MAX_MEMBERS = 64
    MAX_SPANS_PER_MEMBER = 8192
    MAX_WINDOWS_PER_MEMBER = 64
    # a window index waiting on absent reports is force-judged with what
    # arrived after this many seconds — a rank that stopped shipping (obs
    # vetoed there, SIGKILL'd without a membership plane) must not stall
    # the verdict loop forever. NB deliberately wall-clock, not index
    # lag: the straggler is precisely the rank whose windows arrive
    # LAST, so "fast ranks are N windows ahead" is normal, not staleness.
    STALE_S = 15.0

    def __init__(self, detector: Optional[StragglerDetector] = None,
                 member_ranks: Optional[Callable] = None):
        self._lock = threading.Lock()
        self._members: "OrderedDict[int, _MemberTelemetry]" = OrderedDict()
        self.detector = detector or StragglerDetector()
        self.verdicts: deque = deque(maxlen=32)
        self._callbacks: List[Callable] = []
        self._judged_to = -1
        self._pending = (None, 0.0)  # (idx, first seen incomplete)
        # judgeable batches are QUEUED under the main lock (so the queue
        # is globally index-ordered) and drained under this one: the
        # detector's streak logic is order-sensitive and not thread-safe,
        # and two heartbeat handler threads must neither interleave it
        # nor observe window 3 before window 2. Separate from the main
        # lock so an on_straggler callback may call stats()/parts().
        self._judge_queue: deque = deque()
        self._judge_lock = threading.Lock()
        # live-membership view (the PS server wires its elastic state's
        # active ranks here): judging a window index waits for every LIVE
        # rank's report, not just the ranks that happened to ship first —
        # a fast pair must not get judged (and advance the cursor) before
        # the slow rank's window arrives, or the straggler itself would
        # be the one rank the verdict never saw. A dead rank leaves the
        # membership, so it cannot stall judging either.
        self._member_ranks = member_ranks

    def on_straggler(self, fn: Callable) -> "FleetAggregator":
        """Register ``fn(verdict)`` — fired on every straggler/recovered
        verdict (the SLOMonitor ``on_breach`` idiom: exceptions are
        swallowed; a policy hook must never take down the server)."""
        self._callbacks.append(fn)
        return self

    # -- ingest ----------------------------------------------------------
    def add_part(self, cid: int, blob) -> bool:
        """Parse one piggybacked worker part. Returns False (and counts)
        on a garbled blob — a worker's telemetry must never break its
        heartbeat."""
        try:
            part = json.loads(bytes(blob).decode("utf-8"))
            rank = int(part["rank"])
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            from . import inc

            inc("train.fleet.bad_parts")
            return False
        with self._lock:
            m = self._members.get(cid)
            if m is None:
                while len(self._members) >= self.MAX_MEMBERS:
                    self._members.popitem(last=False)
                m = self._members[cid] = _MemberTelemetry()
            m.rank = rank
            m.last_seen = time.monotonic()
            if part.get("pid") is not None:
                m.pid = part["pid"]
            if part.get("wall_epoch") is not None:
                m.wall_epoch = part["wall_epoch"]
            for w in part.get("windows") or ():
                w = _sanitize_window(w)
                if w is None:
                    from . import inc

                    inc("train.fleet.bad_parts")
                    continue
                m.windows[w["w"]] = w
                while len(m.windows) > self.MAX_WINDOWS_PER_MEMBER:
                    m.windows.popitem(last=False)
            spans = part.get("spans")
            if spans:
                m.spans.extend(s for s in spans if isinstance(s, dict))
                if len(m.spans) > self.MAX_SPANS_PER_MEMBER:
                    m.spans = m.spans[-self.MAX_SPANS_PER_MEMBER:]
            if part.get("metrics"):
                m.metrics = part["metrics"]
            self._judge_queue.extend(self._judgeable_locked())
        with self._judge_lock:
            while True:
                try:
                    idx, per_rank = self._judge_queue.popleft()
                except IndexError:
                    break
                try:
                    self._judge(idx, per_rank)
                except Exception:  # noqa: BLE001 — belt and braces: a
                    # detector/judging bug must count, never kill the
                    # heartbeat connection handler that ingested the part
                    from . import inc

                    inc("train.fleet.judge_errors")
        return True

    def forget(self, cid: int) -> None:
        """Drop a pruned member's cached telemetry (the membership plane's
        GC calls this alongside its gauge cleanup)."""
        with self._lock:
            self._members.pop(cid, None)

    def _judgeable_locked(self):
        """Window indices ready to judge, in order:

        - every LIVE rank reported the index (the normal case), or
        - every reporting rank has moved PAST it (windows arrive in
          order, so a skipped index can never complete), or
        - the index sat incomplete for ``STALE_S`` wall seconds (a rank
          that silently stopped shipping must not stall verdicts).

        Returns ``[(idx, {rank: window})]``."""
        if not self._members:
            return []
        # newest entry wins per rank: a restarted worker draws a fresh cid
        # and reuses its rank — the corpse's stale window set must not
        # stall (or double-count) the fleet's judging
        by_rank: Dict[int, _MemberTelemetry] = {}
        for m in self._members.values():
            if not m.windows:
                continue
            cur = by_rank.get(m.rank)
            if cur is None or m.last_seen > cur.last_seen:
                by_rank[m.rank] = m
        per_member = [(r, m.windows) for r, m in by_rank.items()]
        if len(per_member) < 2:
            return []
        expected = len(per_member)
        if self._member_ranks is not None:
            try:
                live = self._member_ranks()
            except Exception:  # noqa: BLE001 — judging must not die on a
                live = None    # membership-view hiccup
            if live:
                # wait for every LIVE rank — the straggler reports its
                # windows LAST, and it is exactly the rank a premature
                # judgment would miss. The live view REPLACES the
                # reporting count (never max): a cleanly-departed member
                # keeps its cached telemetry here by design, and counting
                # its corpse toward `expected` would throttle every
                # post-scale-down window to the STALE_S timeout.
                expected = len(set(live))
        newest = max(max(w) for _r, w in per_member)
        out = []
        now = time.monotonic()
        idx = self._judged_to + 1
        while idx <= newest:
            have = {r: w[idx] for r, w in per_member if idx in w}
            # a reporting rank still BEHIND idx may yet deliver it;
            # one already past it never will (in-order shipping)
            some_behind = any(idx not in w and max(w) < idx
                              for _r, w in per_member)
            complete = len(have) >= expected or (
                not some_behind and len(per_member) >= expected)
            if not complete:
                p_idx, p_t0 = self._pending
                if p_idx != idx:
                    self._pending = (idx, now)
                    break
                if now - p_t0 < self.STALE_S:
                    break  # wait for the laggards to report this index
            if len(have) >= 2:
                out.append((idx, have))
            self._judged_to = idx
            self._pending = (None, 0.0)
            idx += 1
        return out

    def _judge(self, idx: int, per_rank: Dict[int, dict]) -> None:
        from . import event, inc, set_gauge

        events = self.detector.observe(idx, per_rank)
        set_gauge("train.straggler.flagged", len(self.detector.flagged))
        for v in events:
            self.verdicts.append(v)
            if v["kind"] == "straggler":
                inc("train.straggler.verdicts")
                set_gauge(f"train.straggler.rank{v['rank']}", 1)
                event("train.straggler", rank=v["rank"], blame=v["blame"],
                      ratio=v["ratio"], window=v["window"],
                      streak=v["streak"])
            else:
                inc("train.straggler.recoveries")
                set_gauge(f"train.straggler.rank{v['rank']}", 0)
                event("train.straggler.recovered", rank=v["rank"],
                      window=v["window"], was_blamed=v.get("was_blamed"))
            for fn in self._callbacks:
                try:
                    fn(dict(v))
                except Exception:  # noqa: BLE001 — policy hooks must never
                    # take down the telemetry plane (the actuation hooks
                    # run on the PS heartbeat handler thread); counted so
                    # a silently-broken policy is visible in STATS
                    inc("train.straggler.callback_errors")

    # -- answers ---------------------------------------------------------
    def parts(self, drain: bool = True) -> List[dict]:
        """Cached worker parts in the ``obs.telemetry_part`` schema (one
        per rank, role ``rank<r>``). ``drain=True`` empties each member's
        accumulated span cache — repeated collections are increments,
        like every other telemetry pull. Windows stay (the detector's
        history is not a ring to drain)."""
        out = []
        with self._lock:
            for cid, m in self._members.items():
                part = {"pid": m.pid, "role": f"rank{m.rank}",
                        "rank": m.rank, "wall_epoch": m.wall_epoch,
                        "spans": list(m.spans),
                        "metrics": m.metrics or {},
                        "windows": list(m.windows.values())}
                if drain:
                    m.spans = []
                out.append(part)
        return out

    def stats(self) -> dict:
        """The structured "Training fleet" entry for the PS server's
        STATS: per-rank window summaries, live straggler verdicts, and
        verdict history."""
        with self._lock:
            ranks = {}
            for m in self._members.values():
                if m.rank is None or not m.windows:
                    continue
                summary = summarize_windows(m.windows.values())
                if summary is not None:
                    ranks[str(m.rank)] = dict(summary, pid=m.pid)
        return {"ranks": ranks,
                "stragglers": [dict(v) for v in self.detector.flagged
                               .values()],
                "verdicts": [dict(v) for v in self.verdicts]}


# ---------------------------------------------------------------------------
# collection client (tools/train_report.py, tools/fleet_report.py --ps)
# ---------------------------------------------------------------------------

def collect(host: str, port: int, drain: bool = True,
            timeout: float = 30.0) -> dict:
    """One OP_TELEMETRY pull against a PS server → ``{"parts": [...]}`` —
    the server's own part (its RPC lanes + STATS) plus every cached
    worker part. Exactly-once under retries: the request carries a fresh
    collection token; a retried frame whose reply was lost re-serves the
    server's cached reply instead of draining a second batch."""
    from ..kvstore.ps_client import PSClient

    cli = PSClient(host, int(port), timeout=timeout, retries=5,
                   retry_interval=0.2)
    try:
        return cli.telemetry(drain=drain)
    finally:
        cli.close()
