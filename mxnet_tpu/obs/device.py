"""Device-plane observability — XLA cost & memory accounting, MFU/roofline
attribution, and live device-memory telemetry (docs/OBSERVABILITY.md
"Device plane").

The host-side obs plane (trace.py/metrics.py) sees every framework span and
RPC hop but is blind below the jit boundary: no compiled program reported
its FLOPs, bytes, or HBM footprint, so an MFU number could only be
re-measured, never *attributed*. The reference's ``src/profiler`` keeps
per-op device stats and an ``aggregate_stats`` memory table (TBV, SURVEY.md
§5.1); our XLA mapping gets the same facts from the compiler itself:

- **Cost accounting** (:func:`capture`): every compiled-program choke point
  (``optimizer/fused.py``, ``serve/engine.py``, the Executor jit sites,
  CachedOp, ``parallel.ShardedTrainer``) lowers its program through the AOT
  path when capture is active, reads ``compiled.cost_analysis()`` (flops,
  bytes accessed) + ``compiled.memory_analysis()`` (argument/output/temp/
  generated-code bytes), folds the numbers into its own ``compile_log``
  entry, and keeps the *same* compiled executable for execution — one
  compile, measured and run. Records mirror into ``device.*`` metrics and a
  ``device.compile`` instant event (the top-programs table in
  ``tools/trace_report.py``). The (site, label) → cost registry here is
  the program-identity/cost store the persistent AOT program cache
  (``mxnet_tpu/progcache.py``) keys off — both derive identity through
  ``progcache.program_key``, so a cached program and its cost record can
  never disagree.
- **MFU/roofline attribution** (:func:`attribute`): folding an execute
  span's wall duration with its program's cost record gives analytic MFU
  (``flops / dt / peak``) and a roofline class — compute-bound when the
  program's operational intensity (FLOP/byte) clears the machine balance
  point (peak FLOPs / peak bandwidth), bandwidth-bound otherwise — per
  phase (forward/backward/update/serve.execute). ``bench.py`` feeds the
  measured matmul peak in via :func:`set_peak` so the attribution uses the
  same denominator as the measured MFU it sits next to.
- **Live-memory telemetry** (:func:`sample`): a sampled ``device.live_bytes``
  gauge (device ``memory_stats()`` where the backend reports it, the
  ``jax.live_arrays()`` sum elsewhere), exported as a Perfetto counter
  track in the chrome trace and as a Prometheus gauge via the existing
  TELEMETRY plane, with a steady-state :class:`LeakDetector` that flags
  monotonic growth (a retained-array leak) and stays quiet over a
  steady-state fit.

Activation follows the obs contract — zero-cost when off: capture runs
when telemetry is enabled (``obs.enable()`` / ``MXNET_OBS=1``) or when
``MXNET_DEVICE_COST=1`` forces it (how ``bench.py`` captures program costs
without paying span overhead); ``MXNET_DEVICE_COST=0`` forces it off even
with telemetry on (the escape hatch if an exotic backend rejects AOT
lowering).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from . import metrics as _metrics
from . import trace as _trace

__all__ = ["active", "capture", "analyze_compiled", "record", "cost_of",
           "costs", "attribute", "annotate_span", "roofline_class",
           "set_peak", "get_peak", "live_bytes", "sample", "LeakDetector",
           "monitor", "reset"]

# ---------------------------------------------------------------------------
# activation
# ---------------------------------------------------------------------------


def active() -> bool:
    """Should compile sites capture device cost? ``MXNET_DEVICE_COST``
    forces (1) or vetoes (0); default follows the one obs flag."""
    env = os.environ.get("MXNET_DEVICE_COST", "").lower()
    if env in ("1", "true", "yes", "on"):
        return True
    if env in ("0", "false", "no", "off"):
        return False
    return _trace._ENABLED


# ---------------------------------------------------------------------------
# peaks (the MFU denominator and the roofline ceiling)
# ---------------------------------------------------------------------------

# Nominal single-chip numbers; PLACEHOLDERS for backends we can't name —
# bench.py overwrites the flops peak with the slope-measured matmul rate
# (the honest denominator), env vars override both. The tpu row mirrors
# bench.py's NOMINAL_V5E_BF16_TFLOPS/NOMINAL_V5E_HBM_GBPS — keep in sync
# (bench.py defers ALL framework imports for outage-proofing, so it
# cannot import these).
_DEFAULT_PEAKS = {"tpu": (197.0, 819.0),   # v5e bf16 TFLOPs, HBM GB/s
                  "gpu": (312.0, 1555.0),  # A100-class placeholder
                  "cpu": (0.2, 20.0)}      # placeholder; override to taste
_peak_override: list = [None, None]        # [tflops, gbps]


def set_peak(tflops: Optional[float] = None, gbps: Optional[float] = None):
    """Pin the peak compute rate (TFLOP/s) and/or memory bandwidth (GB/s)
    used by MFU/roofline math — bench.py sets the measured matmul peak."""
    if tflops is not None:
        _peak_override[0] = float(tflops)
    if gbps is not None:
        _peak_override[1] = float(gbps)


def get_peak() -> Tuple[float, float]:
    """(peak_tflops, peak_gbps): explicit ``set_peak`` wins, then the
    ``MXNET_DEVICE_PEAK_TFLOPS``/``_GBPS`` env, then a per-backend nominal
    default (a *placeholder* on CPU — the attribution is still internally
    consistent, just not absolute)."""
    tflops, gbps = _peak_override
    if tflops is None:
        env = os.environ.get("MXNET_DEVICE_PEAK_TFLOPS")
        tflops = float(env) if env else None
    if gbps is None:
        env = os.environ.get("MXNET_DEVICE_PEAK_GBPS")
        gbps = float(env) if env else None
    if tflops is None or gbps is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:  # lint-ok: peaks must never take down a caller
            backend = "cpu"
        dt, db = _DEFAULT_PEAKS.get(backend, _DEFAULT_PEAKS["cpu"])
        tflops = dt if tflops is None else tflops
        gbps = db if gbps is None else gbps
    return tflops, gbps


# ---------------------------------------------------------------------------
# cost capture
# ---------------------------------------------------------------------------

# (site, label) → cost record. Sites: "update" (fused engine), "serve",
# "executor", "cachedop", "train_step". The registry the attribution path
# and bench.py read back; bounded by program count (itself bounded by the
# engines' cache-key accounting).
_COSTS: Dict[Tuple[str, str], dict] = {}
_lock = threading.Lock()

# cost-record field order is the compile_log/report schema; keep stable
COST_FIELDS = ("flops", "bytes_accessed", "argument_bytes", "output_bytes",
               "temp_bytes", "generated_code_bytes", "alias_bytes",
               "peak_hbm_bytes")


def analyze_compiled(compiled) -> dict:
    """Extract the cost/memory record from a ``jax.stages.Compiled``.
    Missing analyses (backend-dependent) just leave fields at 0 — the
    record is always structurally complete."""
    cost: dict = {k: 0 for k in COST_FIELDS}
    try:
        ca = compiled.cost_analysis()
        # jax returns a dict on some versions, a 1-elem list on others
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            cost["flops"] = int(ca.get("flops", 0) or 0)
            cost["bytes_accessed"] = int(ca.get("bytes accessed", 0) or 0)
    except Exception:  # lint-ok: cost analysis is best-effort by contract
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = int(getattr(ma, "argument_size_in_bytes", 0))
            out = int(getattr(ma, "output_size_in_bytes", 0))
            tmp = int(getattr(ma, "temp_size_in_bytes", 0))
            code = int(getattr(ma, "generated_code_size_in_bytes", 0))
            alias = int(getattr(ma, "alias_size_in_bytes", 0))
            cost.update(argument_bytes=arg, output_bytes=out, temp_bytes=tmp,
                        generated_code_bytes=code, alias_bytes=alias,
                        # donated buffers alias an argument into an output;
                        # counting both would double the footprint
                        peak_hbm_bytes=max(arg + out + tmp + code - alias, 0))
    except Exception:  # lint-ok: memory analysis is best-effort by contract
        pass
    return cost


def capture(jitted, args: tuple, site: str = None, label: str = None,
            kwargs=None, key=None):
    """AOT-compile ``jitted`` (a ``jax.jit`` wrapper) for the given example
    ``args`` and return ``(compiled, cost)``.

    The caller keeps ``compiled`` as its executable for this signature —
    ONE compile serves both accounting and execution (no double-compile
    tax). On any failure (exotic backend, lowering restriction) returns
    ``(None, None)`` and the caller stays on its ``jax.jit`` path —
    capture must never break dispatch.

    ``key`` takes a :class:`~mxnet_tpu.progcache.ProgramKey` — the ONE
    shared program-identity derivation (``progcache.program_key``): the
    registry files under its (site, label) and the cost record carries
    its digest, so the device plane, ``compile_log`` entries, and the
    persistent program cache can never key the same program differently.
    """
    if key is not None:
        site, label = key.site, key.label
    try:
        lowered = jitted.lower(*args, **(kwargs or {}))
        compiled = lowered.compile()
    except Exception:  # lint-ok: fall back to the jit path, never raise
        return None, None
    cost = analyze_compiled(compiled)
    if key is not None:
        cost = dict(cost, program_key=key.digest)
    record(site, label, cost)
    return compiled, cost


def adopt_cached_cost(key, meta: dict) -> dict:
    """Cost salvage for a persistent program-cache hit
    (``mxnet_tpu/progcache.py``): the writer's compile-time cost analysis
    rides the cache entry's metadata, so the registry/MFU attribution work
    on hits without re-analyzing. Filters ``meta`` down to
    :data:`COST_FIELDS` and — when the device plane records — files it
    under the entry's shared ProgramKey. Returns the cost dict, ``{}``
    when the writer captured none (callers skip an all-zero record)."""
    cost = {k: meta[k] for k in COST_FIELDS if k in meta}
    if not any(cost.values()):
        return {}
    if active():
        record(key.site, key.label, dict(cost, program_key=key.digest))
    return cost


def record(site: str, label: str, cost: dict) -> None:
    """File a program's cost record: the (site,label) registry, the
    ``device.*`` metrics mirror, and a ``device.compile`` instant event
    (the trace-side row ``tools/trace_report.py`` tabulates)."""
    with _lock:
        _COSTS[(site, str(label))] = cost
    if _trace._ENABLED:
        reg = _metrics.registry
        reg.counter("device.compile.count").inc()
        reg.counter("device.compile.flops_total").inc(cost.get("flops", 0))
        reg.counter("device.compile.bytes_total").inc(
            cost.get("bytes_accessed", 0))
        reg.gauge(f"device.{site}.flops").set(cost.get("flops", 0))
        reg.gauge(f"device.{site}.peak_hbm_bytes").set(
            cost.get("peak_hbm_bytes", 0))
        peak = reg.gauge("device.peak_hbm_bytes")
        if cost.get("peak_hbm_bytes", 0) > peak.value:
            peak.set(cost["peak_hbm_bytes"])
        _trace.tracer.event("device.compile", site=site, label=str(label),
                            **{k: cost.get(k, 0)
                               for k in ("flops", "bytes_accessed",
                                         "peak_hbm_bytes")})


def cost_of(site: str, label: str) -> Optional[dict]:
    return _COSTS.get((site, str(label)))


def costs() -> Dict[Tuple[str, str], dict]:
    """Snapshot of every recorded program cost (tests, reports)."""
    with _lock:
        return dict(_COSTS)


# ---------------------------------------------------------------------------
# MFU + roofline attribution
# ---------------------------------------------------------------------------

def roofline_class(cost: Optional[dict], peak_tflops: Optional[float] = None,
                   peak_gbps: Optional[float] = None) -> Optional[dict]:
    """Classify a program against the roofline: its operational intensity
    (FLOP per byte of HBM traffic) vs the machine balance point
    (peak FLOPs / peak bandwidth). Returns None when the record can't
    support the math (zero flops or bytes)."""
    if not cost:
        return None
    flops = cost.get("flops") or 0
    byt = cost.get("bytes_accessed") or 0
    if flops <= 0 or byt <= 0:
        return None
    pt, pb = get_peak()
    if peak_tflops is not None:
        pt = peak_tflops
    if peak_gbps is not None:
        pb = peak_gbps
    intensity = flops / byt
    balance = (pt * 1e12) / (pb * 1e9)
    return {"intensity_flop_per_byte": round(intensity, 3),
            "machine_balance_flop_per_byte": round(balance, 3),
            "bound": "compute" if intensity >= balance else "bandwidth"}


def attribute(phase: str, seconds: float, cost: Optional[dict],
              peak_tflops: Optional[float] = None,
              peak_gbps: Optional[float] = None) -> dict:
    """Fold one program execution (wall ``seconds``) with its cost record:
    returns span attrs ``{analytic_mfu, achieved_tflops, roofline}`` and
    feeds the ``device.mfu.<phase>`` histogram. Phases: forward / backward
    / update / serve.execute (docs/OBSERVABILITY.md). Empty dict when
    there's nothing to attribute — callers splat it into span attrs."""
    if not cost or seconds <= 0:
        return {}
    flops = cost.get("flops") or 0
    if flops <= 0:
        return {}
    pt, pb = get_peak()
    if peak_tflops is not None:
        pt = peak_tflops
    if peak_gbps is not None:
        pb = peak_gbps
    achieved = flops / seconds / 1e12
    mfu = achieved / pt if pt > 0 else 0.0
    rl = roofline_class(cost, pt, pb)
    attrs = {"analytic_mfu": round(mfu, 6),
             "achieved_tflops": round(achieved, 6)}
    if rl:
        attrs["roofline"] = rl["bound"]
    if _trace._ENABLED:
        # MFU is a ratio — fine-grained low buckets, not the latency ladder
        _metrics.registry.histogram(
            f"device.mfu.{phase}",
            buckets=(0.01, 0.02, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7,
                     0.8, 0.9, 1.0)).observe(mfu)
        _metrics.registry.gauge(f"device.{phase}.analytic_mfu").set(
            round(mfu, 6))
    return attrs


def annotate_span(span, phase: str, seconds: float,
                  cost: Optional[dict]) -> dict:
    """``attribute`` + fold the attrs into a live span (before its
    ``__exit__`` records it). No-op on the shared no-op span."""
    attrs = attribute(phase, seconds, cost)
    if attrs and isinstance(span, _trace._Span):
        span.attrs = dict(span.attrs or {}, **attrs)
    return attrs


# ---------------------------------------------------------------------------
# live device memory + leak detection
# ---------------------------------------------------------------------------

def live_bytes() -> int:
    """Current device-resident bytes: the backend allocator's
    ``bytes_in_use`` where reported (TPU/GPU), else the ``jax.live_arrays``
    sum (CPU — the PJRT CPU client reports no memory_stats)."""
    import jax

    total, found = 0, False
    for d in jax.devices():
        try:
            ms = d.memory_stats()
        except Exception:  # lint-ok: stats are optional per backend
            ms = None
        if ms and ms.get("bytes_in_use") is not None:
            total += int(ms["bytes_in_use"])
            found = True
    if found:
        return total
    return int(sum(a.nbytes for a in jax.live_arrays()))


class LeakDetector:
    """Steady-state leak detector over sampled live-bytes.

    A training loop's device footprint is a step function: big at compile
    (temp buffers, donated swaps), then FLAT — parameters update in place.
    Monotonic growth across steady-state steps means something retains
    arrays per step (the classic "append outputs to a list" leak). The
    detector drops ``warmup`` samples (compile/warmup allocations look
    exactly like a leak), then fits a least-squares slope over a sliding
    ``window``; it fires when the slope exceeds ``threshold_bytes_per_step``
    AND the window actually rose end-to-end (slope alone can be a single
    spike's artifact). After firing it re-arms only after a full fresh
    window, so a real leak logs once per window, not once per step.
    """

    def __init__(self, window: int = 10, warmup: int = 3,
                 threshold_bytes_per_step: float = 1 << 20):
        self.window = int(window)
        self.warmup = int(warmup)
        self.threshold = float(threshold_bytes_per_step)
        self._samples: list = []
        self._seen = 0
        self._cooldown = 0
        self.findings: list = []

    def observe(self, nbytes: int) -> Optional[dict]:
        """Feed one sample; returns a finding dict when a leak is flagged
        (and records it in ``findings``), else None."""
        self._seen += 1
        if self._seen <= self.warmup:
            return None
        self._samples.append(float(nbytes))
        if len(self._samples) > self.window:
            self._samples.pop(0)
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        n = len(self._samples)
        if n < self.window:
            return None
        # least-squares slope over x = 0..n-1
        xs = range(n)
        mean_x = (n - 1) / 2.0
        mean_y = sum(self._samples) / n
        sxx = sum((x - mean_x) ** 2 for x in xs)
        sxy = sum((x - mean_x) * (y - mean_y)
                  for x, y in zip(xs, self._samples))
        slope = sxy / sxx if sxx else 0.0
        grew = self._samples[-1] - self._samples[0]
        if slope > self.threshold and grew > self.threshold * (n - 1) / 2:
            finding = {"slope_bytes_per_step": round(slope, 1),
                       "window": n,
                       "grew_bytes": round(grew, 1),
                       "live_bytes": int(self._samples[-1])}
            self.findings.append(finding)
            self._cooldown = self.window
            return finding
        return None

    def reset(self) -> None:
        self._samples.clear()
        self._seen = 0
        self._cooldown = 0
        self.findings.clear()


# the process-global monitor fed by sample(); threshold tuned for real
# leaks (a retained activation is MBs/step), override via env for tests
monitor = LeakDetector(
    window=int(os.environ.get("MXNET_DEVICE_LEAK_WINDOW", "10")),
    threshold_bytes_per_step=float(
        os.environ.get("MXNET_DEVICE_LEAK_BYTES_PER_STEP", str(1 << 20))))


def sample(**attrs) -> Optional[int]:
    """Sample live device bytes into the ``device.live_bytes`` gauge, the
    chrome-trace counter track, and the leak detector. The per-batch call
    sites (Module.fit loop, serve execute) gate on the obs flag via this
    function — one flag check when telemetry is off.

    ``MXNET_OBS_MEMORY=0`` disables sampling even with telemetry on (the
    ``jax.live_arrays`` walk is O(live buffers) on CPU)."""
    if not _trace._ENABLED:
        return None
    if os.environ.get("MXNET_OBS_MEMORY", "").lower() in ("0", "false",
                                                          "no", "off"):
        return None
    n = live_bytes()
    _metrics.registry.gauge("device.live_bytes").set(n)
    _trace.tracer.counter("device.live_bytes", n)
    finding = monitor.observe(n)
    if finding is not None:
        _metrics.registry.counter("device.leak_suspected").inc()
        _trace.tracer.event("device.leak_suspected", **dict(finding, **attrs))
    return n


def reset() -> None:
    """Drop recorded program costs, peaks, and the leak monitor's state
    (tests; a fresh run starts empty)."""
    with _lock:
        _COSTS.clear()
    _peak_override[0] = _peak_override[1] = None
    monitor.reset()
