"""``mxnet_tpu.obs`` — unified runtime telemetry (docs/OBSERVABILITY.md).

Two surfaces, one switch:

- :mod:`~mxnet_tpu.obs.trace` — span tracer. ``obs.trace.span("phase")``
  context managers build a framework-level timeline (per-batch step phases,
  RPCs, checkpoint commits, chaos injections) exportable to chrome-trace
  JSON (Perfetto) or a JSONL stream.
- :mod:`~mxnet_tpu.obs.metrics` — metrics registry. Named counters, gauges,
  and fixed-bucket histograms; ``obs.metrics.dump()`` prints the table,
  ``snapshot()`` returns it as data. The profiler's dispatch counters live
  here too (``dispatch.*``), so ``profiler.count_dispatches()`` and the obs
  layer can never disagree.

The whole layer is **off by default and zero-cost when off**: one module
flag guards every entry point; ``span()`` returns a shared no-op, the
convenience helpers (``inc``/``observe``/``set_gauge``) return immediately.
Turn it on with ``MXNET_OBS=1`` in the environment or ``obs.enable()`` in
code; ``MXNET_OBS_JSONL=<path>`` additionally streams events to a file.

Typical session::

    import mxnet_tpu as mx
    mx.obs.enable()
    module.fit(train_iter, num_epoch=2, checkpoint="ckpts")
    mx.obs.export("trace.json")          # spans + metrics, one file
    print(mx.obs.metrics.dump())         # the metrics table
    # then: python tools/trace_report.py trace.json
"""
from __future__ import annotations

import os
from typing import Optional

from . import context, metrics, trace
# NOTE: the package attribute `obs.export` is the (pre-existing)
# chrome-trace export FUNCTION below; the export MODULE (prometheus text
# + multi-process merge) is reachable as `obs.export_mod` or via its full
# dotted path: `from mxnet_tpu.obs.export import to_prometheus` (python
# resolves that through sys.modules, not the shadowed attribute)
from . import export as export_mod
from . import tail  # tail-based trace retention (verdict at root close)
from . import profile  # continuous sampling profiler
from . import blackbox  # crash flight recorder
from . import slo  # SLO monitor over merged telemetry
from . import device  # device plane: XLA cost/memory accounting, MFU
from . import health  # training-health plane: numerics sentinel + rollback
from . import fleetstats  # training-fleet plane: step attribution, stragglers

__all__ = ["trace", "metrics", "context", "export_mod", "tail", "profile",
           "blackbox", "slo", "device", "health", "fleetstats", "enable",
           "disable", "enabled", "span", "event", "inc", "observe",
           "set_gauge", "export", "reset", "telemetry_part"]

# re-exported hot-path helpers (obs.span is obs.trace.span)
span = trace.span
event = trace.event


def enabled() -> bool:
    """True when telemetry is recording (the one module flag)."""
    return trace._ENABLED


def enable(jsonl: Optional[str] = None) -> None:
    """Turn telemetry on. ``jsonl`` additionally streams every completed
    span/event to that path (appended, flushed per event — survives
    SIGKILL, tail-able on headless workers). A literal ``%p`` in the path
    expands to this process's pid — how a fleet of ProcReplicas sharing
    one ``MXNET_OBS_JSONL`` template each get their own evidence file."""
    trace._ENABLED = True
    if jsonl:
        trace.stream_to(jsonl.replace("%p", str(os.getpid())))


def disable() -> None:
    """Turn telemetry off (the no-op fast path) and close any JSONL
    stream (after appending a final metrics-snapshot record to it).
    Recorded events and metrics are kept until :func:`reset`."""
    was_streaming = trace.tracer._stream is not None
    trace._ENABLED = False
    if was_streaming:
        trace.tracer.stream_metrics(metrics.snapshot())
    trace.stream_to(None)


def reset() -> None:
    """Clear the span ring buffer, drop every metric, and empty the
    device-plane cost registry / leak-monitor state (plus the tail
    plane's pending buffer + exemplars when tail mode is on)."""
    trace.reset()
    metrics.reset()
    device.reset()
    tail.reset()
    fleetstats.reset()


# -- self-gating convenience helpers for instrumentation call sites --------
# One call, one flag check: `obs.inc("kvstore.rpc.retries")` costs a single
# boolean test when telemetry is off.

def inc(name: str, n: int = 1) -> None:
    if trace._ENABLED:
        metrics.registry.counter(name).inc(n)


def observe(name: str, value: float) -> None:
    if trace._ENABLED:
        metrics.registry.histogram(name).observe(value)


def set_gauge(name: str, value: float) -> None:
    if trace._ENABLED:
        metrics.registry.gauge(name).set(value)


def export(path: str) -> str:
    """Write the chrome-trace JSON (spans + instant events + a metrics
    snapshot in ``otherData``) to ``path``. Load it in Perfetto, or feed it
    to ``tools/trace_report.py`` for a terminal breakdown."""
    return trace.export_chrome_trace(path, metrics=metrics.snapshot())


def telemetry_part(drain: bool = True, role: Optional[str] = None) -> dict:
    """This process's contribution to a fleet-wide telemetry collection:
    the drained span ring (or a copy with ``drain=False``), the metrics
    snapshot, and the clock anchor that lets collectors merge many
    processes onto one timeline (obs/export.py ``merge_chrome_parts``).
    This is what a server returns over ``OP_TELEMETRY``."""
    if drain:
        spans = trace.tracer.drain()
    else:
        spans = [trace.tracer._event_dict(r) for r in trace.tracer.events()]
    part = {"pid": os.getpid(), "role": role,
            "wall_epoch": trace.tracer.wall_epoch,
            "sample_rate": context.sample_rate(),
            "spans": spans, "metrics": metrics.snapshot()}
    if tail.enabled():
        # bucket→trace_id exemplars + buffer state ride the part, so one
        # collection carries the exposition's exemplar links and the
        # fleet report can show pending/retained/dropped per member
        part["exemplars"] = tail.exemplars_snapshot()
        part["tail"] = tail.stats()
    return part


# environment switches: MXNET_OBS=1 enables at import, MXNET_OBS_JSONL
# names the stream file (implies enable)
_env = os.environ.get("MXNET_OBS", "").lower()
_jsonl = os.environ.get("MXNET_OBS_JSONL")
if _jsonl or _env not in ("", "0", "false", "no", "off"):
    enable(jsonl=_jsonl)

# the black-box plane's switches (docs/OBSERVABILITY.md): tail retention,
# continuous profiler, flight recorder — each independent, all inherited
# by ProcReplica children so a fleet observes (and crash-records) as one
if os.environ.get("MXNET_OBS_TAIL", "").lower() not in (
        "", "0", "false", "no", "off"):
    tail.enable()
if os.environ.get("MXNET_OBS_PROF", "").lower() not in (
        "", "0", "false", "no", "off"):
    profile.start()
if os.environ.get("MXNET_OBS_BLACKBOX", "").lower() not in (
        "", "0", "false", "no", "off") \
        or os.environ.get("MXNET_OBS_BLACKBOX_DIR"):
    blackbox.enable()
