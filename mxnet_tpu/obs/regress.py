"""Perf-regression dossier over the BENCH_r*.json trajectory.

Every round, the driver captures ``bench.py``'s one JSON line into a
``BENCH_rNN.json`` artifact. Until now the trajectory was compared by
eyeball; this module turns it into a machine-checked dossier:

- **Gains** — each named metric (headline ips, the ``extra.*`` matrix:
  bf16/piped/high legs, BERT seq/s + MFU, LM token rates, serve qps/p99)
  is extracted into a per-round series via one declarative spec table.
- **Noise bands** — the artifacts already carry honesty spreads
  (``*_spread`` = (worst-best)/best across runs); a transition only
  classifies as improvement/regression when the relative delta clears
  ``max(spread_a, spread_b, min_band)`` — inside the band is
  ``within_noise``, exactly the call a human judge was making by hand.
- **Gaps, not zeros** — a ``platform_unavailable`` artifact (the axon
  tunnel outage that voided BENCH_r05: nonzero rc, ``error`` /
  ``platform_error`` keys, null value) is a *gap* in every series. A dead
  tunnel must never register as a 100% regression; transitions skip over
  gap rounds and compare the flanking measurements instead.
- **Anomaly checks** — cross-metric invariants within one round: the
  bf16-piped-slower-than-fp32-piped inversion (bf16 compute is strictly
  more throughput on the same wire; slower means the pipeline or program
  regressed — BENCH_r04's 75 vs 170 ips), and MFU > 1 (a self-
  contradicting denominator, BENCH_r02's 332×).

Exit codes (``tools/bench_compare.py`` returns them; 1 is left to python
itself so an uncaught crash stays distinguishable from a verdict):

- ``EXIT_CLEAN`` (0)      — no regression, no anomaly, no gap
- ``EXIT_REGRESSION`` (2) — at least one out-of-band regression or anomaly
- ``EXIT_GAP`` (3)        — no regression, but the trajectory has holes

Pure stdlib on purpose: ``tools/bench_compare.py`` loads this file without
importing the framework, so the dossier runs anywhere the artifacts do.
"""
from __future__ import annotations

import json
import math
import os
import re
from typing import Dict, List, Optional, Sequence

__all__ = ["EXIT_CLEAN", "EXIT_REGRESSION", "EXIT_GAP", "GAIN_SPECS",
           "load_round", "extract_gains", "compare", "dossier", "render"]

EXIT_CLEAN = 0
EXIT_REGRESSION = 2
EXIT_GAP = 3

# default relative noise floor when an artifact carries no spread for a
# gain (early rounds predate the *_spread fields): single-chip throughput
# jitters a few percent run to run even uncontended
DEFAULT_MIN_BAND = 0.03


def _dig(d: dict, path: str):
    """``"extra.bert_base_bf16.seq_per_sec"`` → nested lookup or None."""
    cur = d
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


# (name, value path, spread path, higher_is_better) — the declarative map
# from bench.py's output schema to named gain series. Spread paths may be
# None (no honesty field for that gain; the min band applies alone).
GAIN_SPECS = (
    ("resnet50_fp32_ips", "value", "extra.fp32_spread", True),
    ("resnet50_bf16_ips", "extra.resnet50_bf16_ips",
     "extra.resnet50_bf16_spread", True),
    ("resnet50_fp32_high_ips", "extra.resnet50_fp32_high_ips",
     "extra.resnet50_fp32_high_spread", True),
    ("resnet50_piped_ips", "extra.resnet50_piped_ips",
     "extra.resnet50_piped_breakdown.spread", True),
    ("resnet50_piped_bf16_ips", "extra.resnet50_piped_bf16_ips",
     "extra.resnet50_piped_bf16_breakdown.spread", True),
    ("bert_seq_per_sec", "extra.bert_base_bf16.seq_per_sec",
     "extra.bert_base_bf16.spread", True),
    ("bert_mfu_vs_measured_peak", "extra.bert_base_bf16.mfu_vs_measured_peak",
     "extra.bert_base_bf16.spread", True),
    ("lm2048_flash_tokens_per_sec", "extra.lm_seq2048_bf16.flash.tokens_per_sec",
     "extra.lm_seq2048_bf16.flash.spread", True),
    ("lm2048_plain_tokens_per_sec", "extra.lm_seq2048_bf16.plain.tokens_per_sec",
     "extra.lm_seq2048_bf16.plain.spread", True),
    ("lm2048_flash_speedup", "extra.lm_seq2048_bf16.flash_speedup",
     None, True),
    ("lm4096_flash_tokens_per_sec", "extra.lm_seq4096_bf16.flash.tokens_per_sec",
     "extra.lm_seq4096_bf16.flash.spread", True),
    ("serve_qps", "extra.serve.serve_qps", None, True),
    ("serve_p99_ms", "extra.serve.serve_p99_ms", None, False),
    # autoregressive decode (docs/SERVING.md "Autoregressive decode"):
    # fleet token throughput and the client-observed inter-token p99
    # under concurrent streams WITH churn — the streaming-UX trajectory
    # numbers; the leg itself gates the program bound and page leaks
    ("decode_tokens_per_s", "extra.decode.decode_tokens_per_s",
     None, True),
    ("decode_p99_per_token_ms", "extra.decode.decode_p99_per_token_ms",
     None, False),
    # replica spawn → readiness-probe-OK with a WARMED persistent program
    # cache (progcache.py; the cold twin rides extra.cold_start.cold_s) —
    # the fleet-elasticity number: what autoscale scale-out actually waits
    ("cold_start_to_ready_s", "extra.cold_start.cold_start_to_ready_s",
     None, False),
    # per-request wire-hop cost with the MXNET_COPYTRACK twin counting
    # (docs/ANALYSIS.md "Data-plane lint"): p50 client latency minus
    # execute, and bytes crossing a copy per request — the committed
    # denominators ROADMAP item 4's zero-copy rewrite must cut >=2x, so
    # the rewrite lands as a classified improvement, not an anecdote
    ("wire_hop_ms_p50", "extra.wire_hop.hop_ms_p50", None, False),
    ("wire_bytes_copied_per_req",
     "extra.wire_hop.bytes_copied_per_request", None, False),
    # bounded-staleness async training (docs/ROBUSTNESS.md "Asynchronous
    # training"): slowest rank's median step time over the fleet median
    # under one slowed rank on the gated-pull wire — ~1 means lockstep
    # coupling, >=2 means only the straggler pays for its own lag
    ("async_step_decoupling", "extra.async_step_decoupling", None, True),
)


def load_round(path: str) -> dict:
    """One BENCH artifact → ``{round, file, gap, reason, gains}``.

    Gap detection is deliberately broad: nonzero rc, a null headline
    value, or an ``error`` / ``platform_error`` key all mean "the platform
    never answered", and the round must contribute NO numbers."""
    with open(path) as f:
        doc = json.load(f)
    parsed = doc.get("parsed") or {}
    m = re.search(r"r?(\d+)", os.path.basename(path))
    rnd = doc.get("n", int(m.group(1)) if m else -1)
    out = {"round": rnd, "file": os.path.basename(path),
           "gap": False, "reason": None, "gains": {}}
    err = parsed.get("error") or _dig(parsed, "platform_error.detail")
    if doc.get("rc", 0) != 0 or parsed.get("value") is None or err:
        out["gap"] = True
        out["reason"] = (str(err)[:200] if err
                         else f"rc={doc.get('rc')} / no headline value")
        return out
    out["gains"] = extract_gains(parsed)
    return out


def extract_gains(parsed: dict) -> Dict[str, dict]:
    """Apply GAIN_SPECS to one parsed bench line."""
    gains = {}
    for name, vpath, spath, hib in GAIN_SPECS:
        v = _dig(parsed, vpath)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not math.isfinite(v):
            continue
        spread = _dig(parsed, spath) if spath else None
        if not isinstance(spread, (int, float)) or spread < 0:
            spread = None
        gains[name] = {"value": float(v), "spread": spread,
                       "higher_is_better": hib}
    return gains


def _check_anomalies(rnd: dict) -> List[dict]:
    """Cross-metric invariants inside one round's gains."""
    out = []
    g = rnd["gains"]

    def val(name):
        return g.get(name, {}).get("value")

    fp32p, bf16p = val("resnet50_piped_ips"), val("resnet50_piped_bf16_ips")
    if fp32p and bf16p and bf16p < fp32p * 0.95:
        out.append({
            "check": "bf16_piped_inversion", "round": rnd["round"],
            "detail": f"bf16-piped {bf16p:g} ips < fp32-piped {fp32p:g} ips "
                      "— bf16 compute must not lose on the same input "
                      "pipeline; the program or pipeline regressed"})
    mfu = val("bert_mfu_vs_measured_peak")
    if mfu is not None and mfu > 1.0:
        out.append({
            "check": "mfu_above_one", "round": rnd["round"],
            "detail": f"MFU {mfu:g} > 1 — the peak denominator "
                      "contradicts the model math; the probe measured "
                      "something other than the chip"})
    return out


def compare(rounds: Sequence[dict],
            min_band: float = DEFAULT_MIN_BAND) -> Dict[str, dict]:
    """Per-gain transition classification over the round sequence.

    Gap rounds contribute no points; each transition compares consecutive
    *measured* points (possibly skipping gaps) and classifies the relative
    delta against the noise band."""
    names: List[str] = []
    for r in rounds:
        for n in r["gains"]:
            if n not in names:
                names.append(n)
    out: Dict[str, dict] = {}
    for name in names:
        series, transitions = [], []
        for r in rounds:
            ent = r["gains"].get(name)
            if r["gap"]:
                series.append({"round": r["round"], "gap": True})
                continue
            if ent is None:
                series.append({"round": r["round"], "missing": True})
                continue
            series.append({"round": r["round"], "value": ent["value"],
                           "spread": ent["spread"]})
        measured = [p for p in series if "value" in p]
        hib = True
        for r in rounds:
            if name in r["gains"]:
                hib = r["gains"][name]["higher_is_better"]
                break
        for a, b in zip(measured, measured[1:]):
            va, vb = a["value"], b["value"]
            if va == 0:
                continue
            delta = (vb - va) / abs(va)
            band = max(a.get("spread") or 0.0, b.get("spread") or 0.0,
                       min_band)
            signed = delta if hib else -delta
            if signed < -band:
                klass = "regression"
            elif signed > band:
                klass = "improvement"
            else:
                klass = "within_noise"
            transitions.append({
                "from_round": a["round"], "to_round": b["round"],
                "delta_pct": round(delta * 100, 2),
                "band_pct": round(band * 100, 2), "class": klass})
        worst = "no_data"
        if transitions:
            classes = [t["class"] for t in transitions]
            worst = ("regression" if "regression" in classes else
                     "improvement" if "improvement" in classes else
                     "within_noise")
        out[name] = {"series": series, "transitions": transitions,
                     "status": worst, "higher_is_better": hib}
    return out


def dossier(paths: Sequence[str],
            min_band: float = DEFAULT_MIN_BAND) -> dict:
    """The full report as data: rounds (with gap attribution), per-gain
    series + classified transitions, anomalies, and the verdict/exit
    code. ``paths`` are BENCH_r*.json files; rounds order by their parsed
    round NUMBER (lexical path sort would put r100 before r99)."""
    rounds = sorted((load_round(p) for p in paths),
                    key=lambda r: r["round"])
    gains = compare(rounds, min_band=min_band)
    anomalies = []
    for r in rounds:
        if not r["gap"]:
            anomalies.extend(_check_anomalies(r))
    regressions = [
        {"gain": name, **t}
        for name, g in gains.items()
        for t in g["transitions"] if t["class"] == "regression"]
    gaps = [{"round": r["round"], "file": r["file"], "reason": r["reason"]}
            for r in rounds if r["gap"]]
    if regressions or anomalies:
        status, code = "regression", EXIT_REGRESSION
    elif gaps:
        status, code = "gap", EXIT_GAP
    else:
        status, code = "clean", EXIT_CLEAN
    return {"rounds": [{k: r[k] for k in ("round", "file", "gap", "reason")}
                       for r in rounds],
            "gains": gains, "anomalies": anomalies,
            "regressions": regressions, "gaps": gaps,
            "min_band": min_band, "status": status, "exit_code": code}


def render(d: dict) -> str:
    """The dossier as a terminal table (the CLI's default output)."""
    lines = []
    w = lines.append
    w(f"perf dossier over {len(d['rounds'])} rounds — status: "
      f"{d['status'].upper()} (exit {d['exit_code']})")
    for r in d["rounds"]:
        tag = f"GAP: {r['reason']}" if r["gap"] else "ok"
        w(f"  r{r['round']:02d}  {r['file']:<22} {tag}")
    w("")
    w(f"{'Gain':<28}{'Trajectory':<44}{'Status':>14}")
    for name, g in d["gains"].items():
        pts = []
        for p in g["series"]:
            if p.get("gap"):
                pts.append("~gap~")
            elif p.get("missing"):
                pts.append("-")
            else:
                pts.append(f"{p['value']:g}")
        w(f"{name:<28}{' -> '.join(pts):<44}{g['status']:>14}")
    if d["regressions"]:
        w("")
        w("Regressions (outside noise band):")
        for t in d["regressions"]:
            w(f"  {t['gain']}: r{t['from_round']:02d} -> r{t['to_round']:02d}"
              f"  {t['delta_pct']:+.1f}% (band ±{t['band_pct']:.1f}%)")
    if d["anomalies"]:
        w("")
        w("Anomalies (cross-metric invariants):")
        for a in d["anomalies"]:
            w(f"  [{a['check']}] r{a['round']:02d}: {a['detail']}")
    if d["gaps"]:
        w("")
        w("Platform gaps (excluded from every comparison):")
        for gp in d["gaps"]:
            w(f"  r{gp['round']:02d} {gp['file']}: {gp['reason']}")
    return "\n".join(lines)
