"""Training-health plane — in-graph numerics telemetry, NaN provenance,
and a divergence sentinel with checkpoint auto-rollback
(docs/OBSERVABILITY.md "Training health").

The host plane (trace/metrics), distributed plane (context/export), and
device plane (device.py) watch the *runtime*; this plane watches the
*model*: loss trajectory, gradient norms, update-to-weight ratios, and
non-finite blowups — the signals that say a run is going bad long before
it crashes, and the machinery to recover when it does.

Three pieces:

- **In-graph numerics stats.** When the plane is active
  (:func:`inline_stats_active`), the fused update engine
  (``optimizer/fused.py``) emits device-resident health scalars as extra
  outputs of the ONE update program it already runs — global grad norm
  (reusing the clipping reduction when clip is on), per-parameter
  grad/param/update norms, per-parameter non-finite counts, and the AMP
  scaler's skip streak. Zero extra program executions; the host sees
  nothing until a sampled step fetches everything with ONE batched
  ``jax.device_get`` (every ``MXNET_OBS_HEALTH_EVERY`` steps). Off, the
  stats vanish from the program and every call site costs one flag check
  (the ``obs`` zero-cost contract).
- **Divergence sentinel** (:class:`HealthMonitor`). EWMA loss-spike,
  grad-norm-explosion, plateau, scaler-skip-streak, and non-finite
  detectors over the sampled series, SLOMonitor-style: thresholds,
  ``on_breach`` callbacks, and an optional auto-action escalation ladder
  (warn → lr backoff → rollback to the last *valid* checkpoint — full
  PR-2 state including RNG and iterator cursor, so the retried segment is
  bitwise-reproducible) with a cooldown and a rollback cap so a poisoned
  batch cannot loop forever.
- **NaN provenance** (:func:`blame_nonfinite`). A fault-only "blame pass"
  that replays the Executor's captured last batch through the graph
  eagerly with per-op finite checks and names the first non-finite node
  (GraphLinter-style node attribution, ``analysis/findings``), emitted as
  a tagged ``health.nan_provenance`` event in the same timeline as the
  breach and the rollback.

Everything lands in the existing surfaces: ``health.*`` gauges/counters/
histograms in the metrics registry (→ Prometheus exposition),
``health.loss`` / ``health.grad_norm`` Perfetto counter tracks in the
chrome trace, tagged ``health.breach`` / ``health.rollback`` /
``health.nan_provenance`` events, and a "Training health" section in
``tools/trace_report.py``.
"""
from __future__ import annotations

import logging
import math
import os
import threading
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from . import metrics as _metrics
from . import trace as _trace

__all__ = ["HealthMonitor", "as_monitor", "enabled", "inline_stats_active",
           "sample_every", "batched_fetch", "apply_lr_backoff",
           "find_rollback_target", "blame_nonfinite", "activate",
           "deactivate"]

log = logging.getLogger("mxnet_tpu.health")

# monitors currently attached to a live training loop — in-graph stats must
# be emitted for them even when the wider obs layer is off (the sentinel
# can act without the tracer recording anything)
_ACTIVE = [0]
_ACTIVE_LOCK = threading.Lock()


def activate() -> None:
    """A training loop attached a HealthMonitor (fit/Trainer call this)."""
    with _ACTIVE_LOCK:
        _ACTIVE[0] += 1


def deactivate() -> None:
    with _ACTIVE_LOCK:
        _ACTIVE[0] = max(0, _ACTIVE[0] - 1)


def enabled() -> bool:
    """Is the health plane on? ``MXNET_OBS_HEALTH`` forces (1) or vetoes
    (0); default: on while a HealthMonitor is attached to a training loop
    (fit ``health=``, ``Trainer.attach_health_monitor``, the estimator's
    HealthHandler). Deliberately NOT keyed to the obs tracing flag: the
    in-graph stats are real device work (per-param norm passes), and
    emitting them for a run that attached nothing to read them would be
    pure waste."""
    env = os.environ.get("MXNET_OBS_HEALTH", "").lower()
    if env in ("1", "true", "yes", "on"):
        return True
    if env in ("0", "false", "no", "off"):
        return False
    return _ACTIVE[0] > 0


# the fused engine asks this before baking health outputs into its program
# (part of its compile-cache key — flipping it mid-run recompiles once)
inline_stats_active = enabled

# per-step stats request: the per-param norms are extra memory passes over
# every weight/grad, so a monitor-driven loop emits them ONLY on steps the
# monitor will sample — the engine keeps two compiled variants (stats /
# plain, bitwise-identical updates) and the overhead amortizes by 1/K.
# None = no loop is gating (plain enabled() behavior: always emit).
_STATS_REQUEST = [None]


def request_stats(flag) -> None:
    """Set by the training loop before each update: True/False gates this
    step's stats variant; None removes the gate (always-on when enabled)."""
    _STATS_REQUEST[0] = flag if flag is None else bool(flag)


def stats_for_this_step() -> bool:
    """What the fused engine consults: the plane is on AND (no per-step
    gate, or the loop asked for stats on this step)."""
    if not enabled():
        return False
    req = _STATS_REQUEST[0]
    return True if req is None else req


def sample_every() -> int:
    """Default sampling period K: fetch + evaluate every K update steps
    (``MXNET_OBS_HEALTH_EVERY``, default 10)."""
    try:
        return max(1, int(os.environ.get("MXNET_OBS_HEALTH_EVERY", "10")))
    except ValueError:
        return 10


def batched_fetch(values: list) -> list:
    """ONE batched device→host transfer for a mixed list of device arrays /
    NDArrays / host values (the PR-3 ``Updater.get_states`` idiom — never
    one blocking ``asnumpy`` per tensor). Counts a single ``d2h`` dispatch.
    This is also what ``monitor.Monitor.toc`` fetches through."""
    import jax

    dev_idx = [i for i, v in enumerate(values)
               if hasattr(v, "_data") or hasattr(v, "devices")
               or type(v).__module__.startswith("jax")]
    out = list(values)
    if dev_idx:
        from .. import profiler

        if profiler.counting_dispatches():
            profiler.count_dispatch("d2h")
        fetched = jax.device_get(
            [getattr(values[i], "_data", values[i]) for i in dev_idx])
        for i, h in zip(dev_idx, fetched):
            out[i] = np.asarray(h)
    return out


def apply_lr_backoff(optimizer, factor: float = 0.5) -> Optional[float]:
    """Back the learning rate off by ``factor``; returns the new lr, or
    None when the optimizer's lr is scheduler-driven (can't be overridden
    — the reference raises on set_learning_rate then)."""
    try:
        new_lr = float(optimizer.learning_rate) * float(factor)
        optimizer.set_learning_rate(new_lr)
    except (RuntimeError, AttributeError, TypeError) as e:
        log.warning("health: lr backoff skipped (%s)", e)
        return None
    if _trace._ENABLED:
        _trace.tracer.event("health.lr_backoff", lr=new_lr, factor=factor)
        _metrics.registry.counter("health.lr_backoffs").inc()
        _metrics.registry.gauge("health.lr").set(new_lr)
    return new_lr


def find_rollback_target(manager, before_step: Optional[int] = None):
    """Newest checkpoint that (a) passes the manager's CRC validation and
    (b) holds only finite float arrays — a CRC-valid checkpoint written
    *after* a NaN blowup is poisoned, not valid. Returns a TrainingState
    or None. Fault-only path: the finite sweep is a host-side scan."""
    from ..checkpoint.manager import CheckpointError

    for step in reversed(manager.list_steps()):
        if before_step is not None and step >= before_step:
            continue
        try:
            state = manager.validate(step)
        except CheckpointError as e:
            log.warning("health: rollback skipping invalid checkpoint: %s", e)
            continue
        poisoned = False
        for name, arr in state.arrays.items():
            if np.issubdtype(arr.dtype, np.floating) and \
                    not np.all(np.isfinite(arr)):
                log.warning("health: rollback skipping checkpoint %d — "
                            "non-finite values in %r", step, name)
                poisoned = True
                break
        if not poisoned:
            return state
    return None


# ---------------------------------------------------------------------------
# the divergence sentinel
# ---------------------------------------------------------------------------

_ESCALATABLE = ("loss_spike", "grad_norm_explosion", "scaler_skip_streak")
_ACTION_ORDER = ("warn", "lr_backoff", "rollback")


class HealthMonitor:
    """Sample-and-judge monitor over a training run's numeric health.

    Feed it once per optimizer step (``step()``); every ``every`` steps it
    fetches the device-resident health scalars with one batched transfer,
    updates the EWMAs, evaluates the detectors, and returns a report dict
    (None between samples and when nothing is enabled).

    Detectors (synthetic-series unit tests in tests/test_health.py):

    - ``nonfinite`` — any non-finite gradient element, loss, or grad norm.
      Fatal: goes straight to the ceiling action (there is nothing an lr
      backoff can do for a NaN already in the parameters). Suppressed when
      an AMP loss scaler is attached: a found-inf step is *skipped* by the
      scaler (params untouched — routine fp16 scale-growth overflow), and
      only a skip *streak* is pathological.
    - ``loss_spike`` — sampled loss > ``loss_spike`` × its EWMA (judged
      against the EWMA *before* the sample folds in).
    - ``grad_norm_explosion`` — global grad norm > ``grad_explosion`` ×
      its EWMA.
    - ``plateau`` — relative loss-EWMA improvement over the last
      ``plateau_window`` samples < ``plateau_eps`` (warn-only: a plateau
      is advice, not an emergency).
    - ``scaler_skip_streak`` — the AMP scaler skipped
      ``skip_streak_threshold``+ consecutive steps (the silent skip-loop:
      counters advance, parameters don't — PR-3's documented quirk). Also
      warned once per streak even below the breach ladder.

    Actions: ``actions`` is the escalation *ceiling* — "warn" (default),
    "lr_backoff", or "rollback". Escalatable breaches climb the ladder on
    consecutive breached samples (warn → lr backoff → rollback); fatal
    ones jump to the ceiling. lr backoff applies in-place when ``step()``
    is given the optimizer; rollback is *requested* via the report (the
    fit loop owns the checkpoint manager and the iterator) and throttled
    here: at most ``max_rollbacks`` per run and never within
    ``rollback_cooldown`` global steps of the last one.
    """

    def __init__(self, every: Optional[int] = None, alpha: float = 0.2,
                 loss_spike: float = 4.0, grad_explosion: float = 10.0,
                 plateau_window: int = 20, plateau_eps: float = 1e-3,
                 skip_streak_threshold: int = 8,
                 actions: str = "warn", lr_backoff_factor: float = 0.5,
                 rollback_cooldown: int = 50, max_rollbacks: int = 2,
                 param_names: Optional[List[str]] = None, logger=None):
        if actions not in ("off",) + _ACTION_ORDER:
            raise ValueError(f"actions must be one of "
                             f"{('off',) + _ACTION_ORDER}, got {actions!r}")
        self.every = int(every) if every else sample_every()
        self.alpha = float(alpha)
        self.loss_spike = float(loss_spike)
        self.grad_explosion = float(grad_explosion)
        self.plateau_window = int(plateau_window)
        self.plateau_eps = float(plateau_eps)
        self.skip_streak_threshold = int(skip_streak_threshold)
        self.actions = actions
        self.lr_backoff_factor = float(lr_backoff_factor)
        self.rollback_cooldown = int(rollback_cooldown)
        self.max_rollbacks = int(max_rollbacks)
        self.param_names = list(param_names) if param_names else None
        self.logger = logger or log
        self._callbacks: List[Callable] = []
        self.last_report: Optional[dict] = None
        self.rollbacks_done = 0
        self._n = 0
        self._pending_loss = None
        self._loss_ewma: Optional[float] = None
        self._gnorm_ewma: Optional[float] = None
        self._ewma_history: deque = deque(maxlen=max(2, self.plateau_window))
        self._ladder = 0
        self._last_rollback_step: Optional[int] = None
        self._warned_streak = False
        self._blamed_episode = False

    # -- feeding -----------------------------------------------------------
    def on_breach(self, fn: Callable) -> "HealthMonitor":
        """Register ``fn(report, breaches)``; returns self for chaining."""
        self._callbacks.append(fn)
        return self

    def attach_names(self, names: List[str]) -> None:
        """Parameter names parallel to the engine's update indices, so a
        breach can name the worst-offending parameter."""
        self.param_names = list(names)

    def will_sample(self) -> bool:
        """Will the NEXT ``step()`` call evaluate? Training loops ask this
        *before* the update runs and pass it to :func:`request_stats`, so
        the fused program emits the stats exactly on sampled steps."""
        return (self._n + 1) % self.every == 0

    def record_loss(self, loss) -> None:
        """Note this step's loss. Cheap by contract: NDArrays / device
        scalars are *referenced*, not synced — the batched fetch at the
        next sampled step moves them to host."""
        self._pending_loss = loss

    def record_metric(self, eval_metric) -> None:
        """Module-path loss source: pick the loss-like metric (loss /
        entropy / perplexity in the name) out of an EvalMetric's running
        values. Host-side floats — no device work."""
        try:
            pairs = eval_metric.get_name_value()
        except Exception:  # noqa: BLE001 — a half-updated metric mid-epoch
            return         # must not take down the health plane
        for name, val in pairs:
            lname = str(name).lower()
            if any(k in lname for k in ("loss", "entropy", "perplexity")):
                self._pending_loss = val
                return

    # -- the sampled evaluation -------------------------------------------
    def step(self, global_step: Optional[int] = None, engine=None,
             scaler=None, optimizer=None, loss=None) -> Optional[dict]:
        """Feed one optimizer step; evaluates every ``self.every`` calls.
        Between samples this is reference bookkeeping only — no device
        work, no allocation beyond a ref swap."""
        if loss is not None:
            self._pending_loss = loss
        self._n += 1
        if self._n % self.every:
            return None
        return self._sample(global_step if global_step is not None
                            else self._n, engine, scaler, optimizer)

    def _sample(self, global_step, engine, scaler, optimizer) -> dict:
        lh = dict(getattr(engine, "last_health", None) or {})
        fetch_keys = list(lh)
        vals = [lh[k] for k in fetch_keys]
        loss_ref = self._pending_loss
        self._pending_loss = None
        if loss_ref is not None and not isinstance(
                loss_ref, (int, float, np.floating)):
            fetch_keys.append("__loss__")
            vals.append(loss_ref)
        host = batched_fetch(vals) if vals else []
        got = dict(zip(fetch_keys, host))

        loss_val: Optional[float] = None
        if "__loss__" in got:
            loss_val = float(np.mean(got["__loss__"]))
        elif loss_ref is not None:
            loss_val = float(loss_ref)

        gnorm = float(got["global_grad_norm"]) \
            if "global_grad_norm" in got else None
        nonfinite_total = int(np.sum(got["nonfinite"])) \
            if "nonfinite" in got else 0
        streak = int(got["skip_streak"]) if "skip_streak" in got else None
        if streak is None and scaler is not None:
            try:
                streak = int(getattr(scaler, "skip_streak", 0) or 0)
            except (TypeError, ValueError):
                streak = None

        # worst update-to-weight ratio + which parameter it belongs to
        ratio_max, worst_param, bad_param = None, None, None
        if "update_norms" in got and "param_norms" in got:
            un = np.asarray(got["update_norms"], np.float64)
            wn = np.asarray(got["param_norms"], np.float64)
            with np.errstate(divide="ignore", invalid="ignore"):
                ratios = un / np.maximum(wn, 1e-12)
            if ratios.size:
                idx = int(np.nanargmax(ratios)) if np.any(
                    np.isfinite(ratios)) else 0
                ratio_max = float(ratios[idx]) if np.isfinite(
                    ratios[idx]) else float("inf")
                worst_param = self._param_name(engine, idx)
        if nonfinite_total and "nonfinite" in got:
            nf = np.asarray(got["nonfinite"])
            bad_param = self._param_name(engine, int(np.argmax(nf)))

        # an AMP scaler that found inf grads SKIPPED this update (params
        # untouched, scale shrunk) — that is the scaler doing its job, not
        # a fatal blowup; the pathological case (skipping forever) is the
        # skip-streak detector's. Only scaler-less runs treat non-finite
        # gradients as fatal.
        scaler_handled = scaler is not None or "found_inf" in lh \
            or "skip_streak" in lh
        breaches = self._evaluate(loss_val, gnorm, nonfinite_total, streak,
                                  ratio_max, worst_param, bad_param,
                                  scaler_handled)
        self._publish(global_step, loss_val, gnorm, nonfinite_total, streak,
                      ratio_max, breaches)
        action, note = self._decide(breaches, global_step)
        if action == "lr_backoff" and optimizer is not None:
            apply_lr_backoff(optimizer, self.lr_backoff_factor)

        report = {
            "step": global_step,
            "loss": loss_val,
            "loss_ewma": self._loss_ewma,
            "grad_norm": gnorm,
            "grad_norm_ewma": self._gnorm_ewma,
            "nonfinite": nonfinite_total,
            "skip_streak": streak,
            "update_ratio_max": ratio_max,
            "worst_param": worst_param,
            "breaches": breaches,
            "action": action,
            "ok": not breaches,
        }
        if note:
            report["note"] = note
        self.last_report = report
        if breaches:
            self.logger.warning(
                "health breach at step %s: %s (action=%s)", global_step,
                "; ".join(b["detail"] for b in breaches), action)
            try:  # snapshot the run's last seconds (spans, metrics,
                # profiler stacks) while the breach evidence is still in
                # the ring — throttled, no-op unless the recorder is armed
                from . import blackbox

                blackbox.trigger(
                    "health_breach:" + ",".join(b["rule"]
                                                for b in breaches))
            except Exception:  # noqa: BLE001 — never fail the train loop
                pass
            for fn in self._callbacks:
                try:
                    fn(report, breaches)
                except Exception:  # noqa: BLE001 — a pager hook must never
                    pass           # take down the training loop
        return report

    def _param_name(self, engine, pos: int) -> Optional[str]:
        idxs = list(getattr(engine, "last_health", {}).get("indices", ())) \
            if engine is not None else []
        if self.param_names:
            # engine indices index the *optimizer's* param table, which is
            # what attach_names mirrors
            i = idxs[pos] if pos < len(idxs) else pos
            if isinstance(i, int) and 0 <= i < len(self.param_names):
                return self.param_names[i]
        if pos < len(idxs):
            return str(idxs[pos])
        return None

    # -- detectors ---------------------------------------------------------
    def _evaluate(self, loss, gnorm, nonfinite_total, streak, ratio_max,
                  worst_param, bad_param,
                  scaler_handled: bool = False) -> List[dict]:
        breaches: List[dict] = []

        bad_loss = loss is not None and not math.isfinite(loss)
        bad_gnorm = gnorm is not None and not math.isfinite(gnorm)
        if (nonfinite_total or bad_loss or bad_gnorm) and not scaler_handled:
            what = []
            if nonfinite_total:
                what.append(f"{nonfinite_total} non-finite gradient "
                            f"element(s)"
                            + (f" (worst: {bad_param})" if bad_param else ""))
            if bad_loss:
                what.append(f"loss={loss}")
            if bad_gnorm:
                what.append(f"grad_norm={gnorm}")
            breaches.append({"rule": "nonfinite",
                             "value": nonfinite_total or float("nan"),
                             "threshold": 0,
                             "param": bad_param,
                             "detail": "non-finite values: "
                                       + ", ".join(what)})

        if (loss is not None and not bad_loss
                and self._loss_ewma is not None
                and math.isfinite(self._loss_ewma)
                and abs(self._loss_ewma) > 1e-12
                and loss > self.loss_spike * self._loss_ewma > 0):
            breaches.append({"rule": "loss_spike", "value": loss,
                             "threshold": self.loss_spike * self._loss_ewma,
                             "detail": f"loss {loss:.6g} > "
                                       f"{self.loss_spike}x EWMA "
                                       f"{self._loss_ewma:.6g}"})

        if (gnorm is not None and not bad_gnorm
                and self._gnorm_ewma is not None
                and math.isfinite(self._gnorm_ewma)
                and self._gnorm_ewma > 1e-12
                and gnorm > self.grad_explosion * self._gnorm_ewma):
            breaches.append({"rule": "grad_norm_explosion", "value": gnorm,
                             "threshold":
                                 self.grad_explosion * self._gnorm_ewma,
                             "detail": f"grad norm {gnorm:.6g} > "
                                       f"{self.grad_explosion}x EWMA "
                                       f"{self._gnorm_ewma:.6g}"})

        if streak is not None:
            if streak >= self.skip_streak_threshold:
                breaches.append({"rule": "scaler_skip_streak",
                                 "value": streak,
                                 "threshold": self.skip_streak_threshold,
                                 "detail": f"AMP scaler skipped {streak} "
                                           "consecutive steps — training "
                                           "is stalled, not progressing"})
                if not self._warned_streak:
                    # warn-once per streak: the silent skip-loop finally
                    # has a voice even when no pager hook is attached
                    self.logger.warning(
                        "AMP scaler skip streak reached %d (threshold %d) "
                        "— counters advance but parameters do not "
                        "(docs/PERFORMANCE.md)", streak,
                        self.skip_streak_threshold)
                    self._warned_streak = True
            elif streak == 0:
                self._warned_streak = False

        # fold the sample into the EWMAs AFTER judging, and only when it
        # did not itself breach: a spike judged against the prior baseline
        # must not become the next sample's baseline (a divergence episode
        # would otherwise normalize itself); non-finite samples never fold
        rules_so_far = {b["rule"] for b in breaches}
        if (loss is not None and math.isfinite(loss)
                and "loss_spike" not in rules_so_far):
            self._loss_ewma = loss if self._loss_ewma is None else \
                (1 - self.alpha) * self._loss_ewma + self.alpha * loss
            self._ewma_history.append(self._loss_ewma)
        if (gnorm is not None and math.isfinite(gnorm)
                and "grad_norm_explosion" not in rules_so_far):
            self._gnorm_ewma = gnorm if self._gnorm_ewma is None else \
                (1 - self.alpha) * self._gnorm_ewma + self.alpha * gnorm

        if (len(self._ewma_history) == self.plateau_window
                and not any(b["rule"] in ("loss_spike", "nonfinite")
                            for b in breaches)):
            first, last = self._ewma_history[0], self._ewma_history[-1]
            denom = max(abs(first), 1e-12)
            improvement = (first - last) / denom
            if improvement < self.plateau_eps:
                breaches.append({"rule": "plateau", "value": improvement,
                                 "threshold": self.plateau_eps,
                                 "detail": f"loss EWMA improved "
                                           f"{improvement:.2e} over last "
                                           f"{self.plateau_window} samples "
                                           f"(< {self.plateau_eps:.0e})"})
                self._ewma_history.clear()  # re-arm over a fresh window

        return breaches

    # -- metrics / trace publication ---------------------------------------
    def _publish(self, global_step, loss, gnorm, nonfinite_total, streak,
                 ratio_max, breaches) -> None:
        if not _trace._ENABLED:
            return
        reg = _metrics.registry
        if loss is not None and math.isfinite(loss):
            reg.gauge("health.loss").set(loss)
            _trace.tracer.counter("health.loss", loss)
        if self._loss_ewma is not None:
            reg.gauge("health.loss_ewma").set(self._loss_ewma)
        if gnorm is not None and math.isfinite(gnorm):
            reg.gauge("health.grad_norm").set(gnorm)
            _trace.tracer.counter("health.grad_norm", gnorm)
            # ratio ladder, not the latency ladder: norms span decades
            reg.histogram("health.grad_norm_hist",
                          buckets=(1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0, 100.0,
                                   1e3, 1e4)).observe(gnorm)
        if ratio_max is not None and math.isfinite(ratio_max):
            reg.gauge("health.update_ratio_max").set(ratio_max)
            reg.histogram("health.update_ratio",
                          buckets=(1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1,
                                   1.0)).observe(ratio_max)
        reg.gauge("health.nonfinite_grads").set(nonfinite_total)
        if nonfinite_total:
            reg.counter("health.nonfinite_total").inc(nonfinite_total)
        if streak is not None:
            reg.gauge("health.scaler.skip_streak").set(streak)
        reg.counter("health.samples").inc()
        for b in breaches:
            reg.counter(f"health.breach.{b['rule']}").inc()
            _trace.tracer.event("health.breach", step=global_step,
                                rule=b["rule"], value=b.get("value"),
                                threshold=b.get("threshold"),
                                detail=b["detail"])

    # -- action policy -----------------------------------------------------
    def _decide(self, breaches, global_step):
        if not breaches or self.actions == "off":
            self._ladder = 0
            self._blamed_episode = False
            return "none", None
        rules = {b["rule"] for b in breaches}
        ceiling = _ACTION_ORDER.index(self.actions) \
            if self.actions in _ACTION_ORDER else 0
        if "nonfinite" in rules:
            want = ceiling  # fatal: jump the ladder
        elif rules & set(_ESCALATABLE):
            self._ladder += 1
            want = min(self._ladder - 1, ceiling)
        else:  # plateau (and anything advisory): never more than a warn
            return "warn", None
        note = None
        if _ACTION_ORDER[want] == "rollback":
            if self.rollbacks_done >= self.max_rollbacks:
                want, note = 0, (f"rollback suppressed: cap of "
                                 f"{self.max_rollbacks} reached")
            elif (self._last_rollback_step is not None
                  and global_step is not None
                  and global_step - self._last_rollback_step
                  < self.rollback_cooldown):
                want, note = 0, (f"rollback suppressed: within cooldown "
                                 f"({self.rollback_cooldown} steps)")
        return _ACTION_ORDER[want], note

    def should_blame(self, report: Optional[dict]) -> bool:
        """One provenance pass per bad episode: True on the first sampled
        breach whose rule warrants attribution (non-finite values or a
        scaler skip-loop); re-arms after a clean sample."""
        if not report or not report.get("breaches"):
            return False
        rules = {b["rule"] for b in report["breaches"]}
        if not (rules & {"nonfinite", "scaler_skip_streak"}):
            return False
        if self._blamed_episode:
            return False
        self._blamed_episode = True
        return True

    def note_rollback(self, restored_step: int) -> None:
        """The fit loop rolled back to ``restored_step``: start cooldown,
        reset the sampled series (the replayed segment is a fresh run —
        stale EWMAs would re-judge it against a poisoned baseline)."""
        self.rollbacks_done += 1
        self._last_rollback_step = restored_step
        self.reset_series()
        if _trace._ENABLED:
            _metrics.registry.counter("health.rollbacks").inc()

    def reset_series(self) -> None:
        self._pending_loss = None
        self._loss_ewma = None
        self._gnorm_ewma = None
        self._ewma_history.clear()
        self._ladder = 0
        self._blamed_episode = False
        self._warned_streak = False


def as_monitor(spec) -> Optional[HealthMonitor]:
    """Coerce a fit-API ``health=`` argument: None | True | dict of
    HealthMonitor kwargs | a HealthMonitor instance."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, HealthMonitor):
        return spec
    if spec is True:
        return HealthMonitor()
    if isinstance(spec, dict):
        return HealthMonitor(**spec)
    raise TypeError(f"health must be None/True/dict/HealthMonitor, "
                    f"got {type(spec)}")


# ---------------------------------------------------------------------------
# NaN provenance — the fault-only blame pass
# ---------------------------------------------------------------------------

def blame_nonfinite(executor, max_report_inputs: int = 4) -> Optional[dict]:
    """Replay the Executor's captured last batch through the graph eagerly
    with per-op finite checks; name the first node whose output is
    non-finite (and any already-non-finite leaf inputs feeding it — a
    poisoned batch blames the data, a bad op blames the op).

    Fault-only by design: one eager per-node walk with a host check per
    node. Never called on the hot path; returns None when the executor
    holds no captured batch (forward(is_train=True) not run, or
    grad_req="null"). The finding is emitted as a tagged
    ``health.nan_provenance`` event and returned as a dict built on the
    GraphLinter finding machinery (``analysis/findings``)."""
    import jax
    import jax.random as jr

    from .. import autograd
    from .. import random as _random
    from ..analysis.findings import Finding, Severity
    from ..ops import get_op
    from ..ops.registry import coerce_kwargs

    li = getattr(executor, "_last_inputs", None)
    if li is None:
        return None
    key_data, arg_vals, aux_vals, train = li
    symb = executor._symbol
    arg_names = symb.list_arguments()
    aux_names = symb.list_auxiliary_states()

    def _finite(v) -> bool:
        a = np.asarray(jax.device_get(v))
        if not np.issubdtype(a.dtype, np.floating) and \
                not np.issubdtype(a.dtype, np.complexfloating):
            return True
        return bool(np.all(np.isfinite(a)))

    # leaf inputs first: a poisoned batch / corrupted parameter is the
    # provenance answer even before any op runs
    bad_inputs = [n for n, v in zip(arg_names, arg_vals) if not _finite(v)]
    bad_inputs += [n for n, v in zip(aux_names, aux_vals) if not _finite(v)]

    rng_key = key_data
    if hasattr(jr, "wrap_key_data") and \
            getattr(rng_key, "dtype", None) is not None and \
            str(getattr(rng_key, "dtype", "")) == "uint32":
        rng_key = jr.wrap_key_data(rng_key)

    first_bad = None
    checked = 0
    env: dict = {}
    args = dict(zip(arg_names, arg_vals))
    auxs = dict(zip(aux_names, aux_vals))
    old_train = autograd.set_training(bool(train))
    try:
        with _random.trace_key_scope(rng_key):
            for node in symb._topo():
                if node._op is None:
                    env[id(node)] = args[node._name] if node._name in args \
                        else auxs[node._name]
                    continue
                if node._op == "_group":
                    continue
                opdef = getattr(node, "_opdef", None) or get_op(node._op)
                kwargs = coerce_kwargs({k: v for k, v in node._attrs.items()
                                        if not k.startswith("__")})
                in_vals = []
                for i in node._inputs:
                    v = env[id(i._base())]
                    if i._index is not None and isinstance(v, tuple):
                        v = v[i._index]
                    in_vals.append(v)
                if node._op == "BatchNorm" and train and \
                        not kwargs.get("use_global_stats", False):
                    kwargs["output_mean_var"] = True
                    out, _bm, _bv = opdef.fn(*in_vals, **kwargs)
                else:
                    out = opdef.fn(*in_vals, **kwargs)
                env[id(node)] = out
                checked += 1
                outs = out if isinstance(out, tuple) else (out,)
                if not all(_finite(o) for o in outs):
                    bad_in = [i._base()._name or i._base()._op
                              for i, v in zip(node._inputs, in_vals)
                              if not _finite(v)]
                    first_bad = {"node": node._name or node._op,
                                 "op": node._op,
                                 "nonfinite_inputs":
                                     bad_in[:max_report_inputs]}
                    break
    finally:
        autograd.set_training(old_train)

    if first_bad is None and not bad_inputs:
        # the forward replay is clean: the non-finite values arose in
        # backward or in the update itself (classic fp16 loss-scale
        # overflow) — say so rather than inventing a node
        result = {"node": None, "op": None, "nonfinite_inputs": [],
                  "checked_nodes": checked,
                  "detail": "forward replay is finite — non-finite values "
                            "arose in backward or the optimizer update "
                            "(loss-scale overflow?)"}
    else:
        fb = first_bad or {}
        finding = Finding(
            rule_id="nonfinite-value",
            severity=Severity.ERROR,
            message=("first non-finite output at this node"
                     if first_bad else "non-finite graph input"),
            node=fb.get("node") or (bad_inputs[0] if bad_inputs else None),
            op=fb.get("op"),
            fix_hint="inspect the named tensor; the health sentinel can "
                     "auto-rollback past it (docs/OBSERVABILITY.md)")
        result = {"node": finding.node, "op": finding.op,
                  "nonfinite_inputs":
                      (fb.get("nonfinite_inputs") or bad_inputs)
                      [:max_report_inputs],
                  "checked_nodes": checked,
                  "detail": finding.format()}
    log.warning("health: NaN provenance — %s", result["detail"]
                if "detail" in result else result)
    if _trace._ENABLED:
        _metrics.registry.counter("health.nan_provenance").inc()
        _trace.tracer.event("health.nan_provenance",
                            node=result.get("node"), op=result.get("op"),
                            nonfinite_inputs=result.get("nonfinite_inputs"),
                            checked_nodes=result.get("checked_nodes"))
    return result
