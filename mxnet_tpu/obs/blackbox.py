"""Crash flight recorder — "what was this process doing in its last
seconds" as an artifact (docs/OBSERVABILITY.md "Flight recorder").

A crashed or watchdogged process used to leave only whatever JSONL
happened to flush. This module keeps a cheap **always-on ring** of the
most recent telemetry — every completed span / instant event / counter
sample (fed once, at creation, from ``obs.trace`` — including tail-held
spans that would later be dropped) — and can serialize it plus a metrics
snapshot, the continuous profiler's recent samples + folded stacks, every
thread's python stack, and the tail buffer's state into one timestamped
**bundle**:

- ``dump(reason)`` — explicit, and wired into: the tsan deadlock watchdog
  (``tsan.dump_stacks``), SLO breaches (``obs/slo.py``), health-sentinel
  breaches (``obs/health.py``), fatal-signal hooks (SIGTERM/SIGABRT,
  chained to any existing handler), the uncaught-exception hook, and the
  serve wire's ``DUMP`` opcode (``wire.py``, ``serve/server.py``) so an
  operator can snapshot a live replica remotely;
- a **periodic flush** (``MXNET_OBS_BLACKBOX_FLUSH_S``, default 2s)
  atomically rewrites ``blackbox-<pid>-last.json`` in the bundle dir — a
  SIGKILL cannot be hooked, so the recorder leaves a ≤flush-period-stale
  bundle behind instead; ``faulthandler`` is armed at the same path root
  (``blackbox-<pid>.stacks``) for C-level faults python never sees.

Bundles are plain JSON with a ``{"blackbox": 1}`` marker;
``tools/trace_report.py`` and ``tools/fleet_report.py`` read them back
into the merged timeline (span lanes + a ``prof:<phase>`` profiler lane
attributing the corpse's last seconds by phase).

Repeated automatic dumps are throttled (``MXNET_OBS_BLACKBOX_COOLDOWN_S``,
default 30s) so a breach storm cannot turn the recorder into the outage.
"""
from __future__ import annotations

import faulthandler
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import List, Optional

from . import metrics as _metrics
from . import trace as _trace
from ._env import env_float as _env_float

__all__ = ["FlightRecorder", "recorder", "enabled", "enable", "disable",
           "bundle", "dump", "trigger", "is_bundle", "read_bundle"]


class FlightRecorder:
    """Bounded ring of recent telemetry + bundle serialization."""

    def __init__(self, capacity: Optional[int] = None,
                 dirpath: Optional[str] = None,
                 flush_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 role: Optional[str] = None):
        cap = int(capacity) if capacity \
            else int(_env_float("MXNET_OBS_BLACKBOX_EVENTS", 4096))
        self._ring: deque = deque(maxlen=cap)
        self.dirpath = dirpath
        self.flush_s = flush_s if flush_s is not None \
            else _env_float("MXNET_OBS_BLACKBOX_FLUSH_S", 2.0)
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else _env_float("MXNET_OBS_BLACKBOX_COOLDOWN_S", 30.0)
        self.role = role
        self.dumps = 0
        self.flushes = 0
        self._last_trigger = 0.0
        self._dirty = False
        self._stop_evt = threading.Event()
        self._writer: Optional[threading.Thread] = None

    # -- intake (the trace._BLACKBOX_SINK) ------------------------------
    def feed(self, rec: tuple, tracer) -> None:
        # raw tuples: one deque append on the span hot path; normalization
        # is deferred to bundle time (rare)
        self._ring.append(rec)
        self._dirty = True

    # -- bundle ---------------------------------------------------------
    def bundle_dict(self, reason: str = "manual",
                    thread_stacks: bool = True) -> dict:
        tracer = _trace.tracer
        events = [tracer._event_dict(r) for r in list(self._ring)]
        out = {
            "blackbox": 1,
            "reason": reason,
            "pid": os.getpid(),
            "role": self.role,
            "wall_epoch": tracer.wall_epoch,
            "dumped_at": time.time(),
            "events": events,
            "metrics": _metrics.snapshot(),
        }
        try:
            from . import profile as _profile
            if _profile.profiler is not None:
                p = _profile.profiler
                # a bounded slice, not the whole ring: the 65536-sample
                # buffer covers ~16 min at 67 Hz, and the periodic flush
                # runs every flush_s — copying/coalescing it all each
                # time makes the "cheap always-on" path O(ring) forever.
                # The bundle promises the LAST SECONDS anyway
                prof_s = _env_float("MXNET_OBS_BLACKBOX_PROF_S", 10.0)
                out["profiler"] = {
                    "stats": p.stats(),
                    "phase_seconds": p.phase_seconds(),
                    "folded": p.folded(top=200),
                    "samples": p.chrome_events(seconds=prof_s),
                }
        except Exception:  # noqa: BLE001 — a bundle with less beats none
            pass
        try:
            from . import tail as _tail
            st = _tail.stats()
            if st is not None:
                out["tail"] = st
        except Exception:  # noqa: BLE001
            pass
        if thread_stacks:
            try:
                names = {t.ident: t.name for t in threading.enumerate()}
                stacks = {}
                for tid, frame in sys._current_frames().items():
                    stacks[f"{names.get(tid, '?')} ({tid})"] = \
                        traceback.format_stack(frame, limit=16)
                out["threads"] = stacks
            except Exception:  # noqa: BLE001
                pass
        return out

    def _path(self, tag: str) -> str:
        return os.path.join(self.dirpath or ".",
                            f"blackbox-{os.getpid()}-{tag}.json")

    def _write(self, doc: dict, path: str) -> str:
        # atomic: a reader (or the next crash) must never see a torn
        # bundle — tmp + rename on the same filesystem
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        return path

    def dump(self, reason: str = "manual",
             path: Optional[str] = None,
             doc: Optional[dict] = None) -> str:
        """Serialize a bundle to ``path`` (default: a timestamped file in
        the bundle dir). Returns the path. ``doc`` persists an
        already-built bundle (the DUMP opcode writes the same document it
        replies with, instead of a second, later snapshot)."""
        if doc is None:
            doc = self.bundle_dict(reason)
        if path is None:
            if self.dirpath:
                os.makedirs(self.dirpath, exist_ok=True)
            path = self._path(str(int(time.time() * 1e3)))
        out = self._write(doc, path)
        self.dumps += 1
        if _trace._ENABLED:
            _metrics.registry.counter("blackbox.dumps").inc()
            _trace.tracer.event("blackbox.dump", reason=reason, path=out)
        return out

    def trigger(self, reason: str, **attrs) -> Optional[str]:
        """Throttled automatic dump (watchdog / SLO / health hooks): at
        most one per cooldown window; silently a no-op between windows so
        a breach storm cannot become an IO storm."""
        now = time.monotonic()
        if now - self._last_trigger < self.cooldown_s:
            if _trace._ENABLED:
                _metrics.registry.counter("blackbox.throttled").inc()
            return None
        self._last_trigger = now
        try:
            return self.dump(reason)
        except OSError:
            return None

    # -- periodic flush (the SIGKILL answer) -----------------------------
    def start_writer(self) -> None:
        if self.dirpath is None or self.flush_s <= 0:
            return
        if self._writer is not None and self._writer.is_alive():
            return
        os.makedirs(self.dirpath, exist_ok=True)
        self._stop_evt.clear()
        self._writer = threading.Thread(target=self._flush_loop,
                                        daemon=True,
                                        name="mxtpu-blackbox-writer")
        self._writer.start()

    def flush(self) -> Optional[str]:
        """One atomic rewrite of ``blackbox-<pid>-last.json`` (skipped
        when nothing new arrived). Thread stacks are skipped on the
        periodic path — they are crash detail, not steady-state state."""
        if not self._dirty or self.dirpath is None:
            return None
        self._dirty = False
        try:
            path = self._write(self.bundle_dict("flush",
                                                thread_stacks=False),
                               self._path("last"))
        except OSError:
            return None
        self.flushes += 1
        return path

    def _flush_loop(self) -> None:
        while not self._stop_evt.wait(self.flush_s):
            try:
                self.flush()
            except Exception:  # noqa: BLE001 — the recorder must never
                pass           # take down what it records

    def stop(self) -> None:
        self._stop_evt.set()
        if self._writer is not None:
            self._writer.join(timeout=2)
            if self._writer.is_alive():
                # a flush stuck on a dead filesystem: it is a daemon and
                # the stop event is set, so it dies with the process —
                # but count the leak instead of pretending it joined
                _metrics.registry.counter("blackbox.writer_leaked").inc()
            self._writer = None

    def stats(self) -> dict:
        return {"events": len(self._ring), "dumps": self.dumps,
                "flushes": self.flushes, "dir": self.dirpath,
                "flush_s": self.flush_s, "cooldown_s": self.cooldown_s}


# ---------------------------------------------------------------------------
# module singleton + hooks
# ---------------------------------------------------------------------------

recorder: Optional[FlightRecorder] = None
_prev_excepthook = None
_prev_sig: dict = {}


def enabled() -> bool:
    return recorder is not None


def enable(dirpath: Optional[str] = None, *,
           capacity: Optional[int] = None, flush_s: Optional[float] = None,
           cooldown_s: Optional[float] = None, role: Optional[str] = None,
           signals: bool = True) -> FlightRecorder:
    """Arm the flight recorder. With ``dirpath`` (or
    ``MXNET_OBS_BLACKBOX_DIR``): periodic last-bundle flush + faulthandler
    + fatal-signal/excepthook dumps land there; without, the ring still
    records and ``dump(path=...)`` / the DUMP opcode work."""
    global recorder
    if recorder is not None:
        disable()
    dirpath = dirpath or os.environ.get("MXNET_OBS_BLACKBOX_DIR") or None
    recorder = FlightRecorder(capacity=capacity, dirpath=dirpath,
                              flush_s=flush_s, cooldown_s=cooldown_s,
                              role=role)
    _trace._BLACKBOX_SINK = recorder.feed
    recorder.start_writer()
    if dirpath:
        try:  # C-level faults (SEGV/ABRT in native code) bypass python —
            # faulthandler at least leaves the thread stacks on disk
            os.makedirs(dirpath, exist_ok=True)
            f = open(os.path.join(
                dirpath, f"blackbox-{os.getpid()}.stacks"), "w")
            faulthandler.enable(file=f)
        except OSError:
            pass
    if signals:
        _install_hooks()
    return recorder


def disable() -> None:
    global recorder
    _uninstall_hooks()
    if recorder is not None:
        recorder.stop()
    _trace._BLACKBOX_SINK = None
    recorder = None


def bundle(reason: str = "manual") -> dict:
    """The in-memory bundle (the DUMP opcode's payload). Works with the
    recorder disarmed too — the ring is then empty but metrics, profiler
    state, and thread stacks still tell the story."""
    r = recorder if recorder is not None else FlightRecorder(capacity=1)
    return r.bundle_dict(reason)


def dump(reason: str = "manual", path: Optional[str] = None,
         doc: Optional[dict] = None) -> Optional[str]:
    return recorder.dump(reason, path=path, doc=doc) \
        if recorder is not None else None


def trigger(reason: str, **attrs) -> Optional[str]:
    """Throttled hook entry point for the watchdog / SLO / health planes
    (no-op unless the recorder is armed)."""
    return recorder.trigger(reason, **attrs) if recorder is not None \
        else None


# -- fatal-signal / excepthook chains ---------------------------------------

def _dump_from_signal(reason: str, timeout: float = 5.0) -> None:
    """Dump from a signal handler WITHOUT deadlocking the process: the
    handler runs on the main thread, whose interrupted frame may hold any
    of the non-reentrant locks ``bundle_dict`` needs (a histogram's
    ``observe`` lock, the tail buffer's, the profiler's). Serializing on
    a side thread and joining with a bound turns that worst case into a
    lost bundle instead of a SIGTERM that never terminates."""
    done = threading.Event()

    def work():
        try:
            if recorder is not None:
                recorder.dump(reason)
        except Exception:  # noqa: BLE001 — dying anyway
            pass
        finally:
            done.set()

    t = threading.Thread(target=work, daemon=True,
                         name="mxtpu-blackbox-sigdump")
    t.start()
    done.wait(timeout)


def _install_hooks() -> None:
    global _prev_excepthook
    if _prev_excepthook is None:
        _prev_excepthook = sys.excepthook

        def hook(tp, val, tb):
            try:
                if recorder is not None:
                    recorder.trigger(f"uncaught:{tp.__name__}")
            finally:
                _prev_excepthook(tp, val, tb)

        sys.excepthook = hook
    if threading.current_thread() is not threading.main_thread():
        return  # signal.signal only works on the main thread
    for signum in (signal.SIGTERM, signal.SIGABRT):
        if signum in _prev_sig:
            continue
        try:
            prev = signal.getsignal(signum)

            def handler(sig, frame, _prev=prev):
                if recorder is not None:
                    _dump_from_signal(f"signal:{signal.Signals(sig).name}")
                if callable(_prev):
                    _prev(sig, frame)
                elif _prev is not signal.SIG_IGN:
                    # default disposition: restore it and re-raise so the
                    # process still dies with the right status; an
                    # explicit SIG_IGN stays ignored — arming the
                    # recorder must not make an ignored signal fatal
                    signal.signal(sig, signal.SIG_DFL)
                    os.kill(os.getpid(), sig)

            signal.signal(signum, handler)
            _prev_sig[signum] = prev
        except (ValueError, OSError):  # non-main thread / exotic platform
            pass


def _uninstall_hooks() -> None:
    global _prev_excepthook
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None
    for signum, prev in list(_prev_sig.items()):
        try:
            signal.signal(signum, prev)
        except (ValueError, OSError):
            pass
        _prev_sig.pop(signum, None)


# -- bundle readers (tools/trace_report.py, tools/fleet_report.py) ----------

def is_bundle(doc) -> bool:
    return isinstance(doc, dict) and doc.get("blackbox") == 1


def read_bundle(doc: dict) -> dict:
    """A bundle as a telemetry *part* (the ``obs.telemetry_part`` schema
    plus the profiler lane already folded into ``spans``), so the merge
    tooling treats a corpse's bundle exactly like a live replica's
    telemetry."""
    spans: List[dict] = list(doc.get("events") or ())
    prof = doc.get("profiler") or {}
    spans.extend(prof.get("samples") or ())
    spans.sort(key=lambda e: e.get("ts", 0.0))
    return {"pid": doc.get("pid"),
            "role": doc.get("role") or f"blackbox:{doc.get('reason')}",
            "wall_epoch": doc.get("wall_epoch"),
            "spans": spans,
            "metrics": doc.get("metrics") or {},
            "blackbox_reason": doc.get("reason"),
            "dumped_at": doc.get("dumped_at")}
