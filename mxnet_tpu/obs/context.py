"""Distributed trace context — one trace_id across client, router, and
every replica (docs/OBSERVABILITY.md "Distributed tracing").

PR 4's tracer is strictly per-process: spans nest on a thread-local stack
and die in that process's ring buffer. But one INFER now crosses 3+
processes (client → FleetServer front → ProcReplica over the serve wire),
so the per-process timelines are disjoint fragments of the same request.
This module is the thread that stitches them: a W3C-traceparent-style
context (``trace_id``, parent ``span_id``, sampled flag) that

- rides the **existing wire framing's key field** (``u16 key_len | key`` —
  empty on every serve opcode, the parameter name on PS RPCs), appended
  after an ASCII unit separator (``\\x1f``). Old-format frames have no
  separator and parse unchanged (context optional, absent = new root at
  the server); a context-bearing key is split before any key lookup, so
  the server *strips* context it does not want. No frame layout changed.
- is carried per **thread** (the tracer's nesting idiom): ``use(ctx)``
  activates a context for a block, every span opened inside allocates a
  child ``span_id`` and re-activates itself, so remote children hang off
  the exact span that sent the RPC.
- implements **head-based sampling**: the decision is made ONCE where the
  trace is born (``new_root()``) and propagated in the flags byte —
  ``MXNET_OBS_SAMPLE=0.1`` traces 1 request in 10 end to end and the other
  9 cost one thread-local read per span site on every hop. That is what
  lets tracing stay on under production load (the ``obs_overhead_pct``
  bench gain measures it).

Wire header format (W3C traceparent, version 00)::

    00-<32 hex trace_id>-<16 hex span_id>-<2 hex flags>

flags bit 0 = sampled. Unknown versions/garbage parse to ``None`` (treated
as absent — a malformed header must never fail an RPC).

``MXNET_OBS_WIRE=0`` suppresses injection entirely (escape hatch for
peers that predate context, e.g. the native C++ PS server, which would
treat a suffixed key as a different parameter).
"""
from __future__ import annotations

import os
import random
import re
import threading
from typing import Optional, Tuple

__all__ = ["TraceContext", "current", "use", "new_root", "new_span_id",
           "new_trace_id", "from_header", "inject_key", "extract_key",
           "sample_rate", "set_sample_rate", "set_tail_mode", "tail_mode",
           "set_force_retain", "get_force_retain", "CTX_SEP"]

# ASCII unit separator: cannot appear in a sane parameter name, invisible
# to old parsers (they see one longer key only if a NEW client talks to an
# OLD server — which MXNET_OBS_WIRE=0 exists for)
CTX_SEP = "\x1f"

_HEADER_RE = re.compile(r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

# head-based sampling rate for NEW roots (children inherit the flag)
_sample_rate = 1.0
_v = os.environ.get("MXNET_OBS_SAMPLE")
if _v:
    try:
        _sample_rate = min(max(float(_v), 0.0), 1.0)
    except ValueError:
        pass

# escape hatch: never put context on the wire (old peers)
_WIRE = os.environ.get("MXNET_OBS_WIRE", "1").lower() not in (
    "0", "false", "no", "off")

# tail mode (obs/tail.py): new roots carry the TAIL flag — spans record
# into the pending buffer and the keep-or-drop decision moves to root
# close. Flipped by tail.enable()/disable(); this module only owns the bit
_tail_mode = False

_local = threading.local()
_rng = random.Random(int.from_bytes(os.urandom(8), "little"))


def set_tail_mode(on: bool) -> None:
    global _tail_mode
    _tail_mode = bool(on)


def tail_mode() -> bool:
    return _tail_mode


def set_force_retain(on: bool) -> None:
    """Thread-local force-retain: roots born while set carry the FORCE
    flag (recorded durably on every hop, bypassing the tail policy)."""
    _local.force = bool(on)


def get_force_retain() -> bool:
    return getattr(_local, "force", False)


def sample_rate() -> float:
    return _sample_rate


def set_sample_rate(rate: float) -> None:
    """Set the head-sampling probability for new roots (0.0–1.0)."""
    global _sample_rate
    _sample_rate = min(max(float(rate), 0.0), 1.0)


def _id_rng() -> random.Random:
    # one PRNG per thread, OS-seeded once: a urandom SYSCALL per id was
    # the single hottest instruction on the span path (36% of it), and
    # under tail mode every request mints a root — tolerable at
    # head-sample 0.1, not at record-everything. 128 bits of OS entropy
    # seed each thread's stream; ids only need uniqueness, not secrecy.
    r = getattr(_local, "idrng", None)
    if r is None:
        r = _local.idrng = random.Random(
            int.from_bytes(os.urandom(16), "little"))
    return r


def new_trace_id() -> str:
    return f"{_id_rng().getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_id_rng().getrandbits(64):016x}"


class TraceContext:
    """An immutable (trace_id, span_id, flags) triple. ``span_id`` is
    the *current parent*: a span opened under this context records it as
    its parent and substitutes its own id for the duration.

    Flags (the wire header's 2-hex byte): bit 0 ``sampled`` (head-based —
    record durably on every hop), bit 1 ``tail`` (tail-pending: record
    into the pending buffer, verdict at root close — obs/tail.py), bit 2
    ``force`` (force-retain: record durably AND log a retain verdict)."""

    __slots__ = ("trace_id", "span_id", "sampled", "tail", "force")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True,
                 tail: bool = False, force: bool = False):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)
        self.tail = bool(tail)
        self.force = bool(force)

    def child(self) -> "TraceContext":
        """Same trace, fresh span id, inherited sampling decision."""
        return TraceContext(self.trace_id, new_span_id(), self.sampled,
                            tail=self.tail, force=self.force)

    @property
    def records(self) -> bool:
        """Does a span under this context record at all (durably or
        pending)? The span-site gate: NOT records → shared no-op."""
        return self.sampled or self.tail or self.force

    def to_header(self) -> str:
        flags = ((0x01 if self.sampled else 0)
                 | (0x02 if self.tail else 0)
                 | (0x04 if self.force else 0))
        return f"00-{self.trace_id}-{self.span_id}-{flags:02x}"

    def __repr__(self):
        return (f"TraceContext({self.trace_id[:8]}…/{self.span_id}, "
                f"sampled={self.sampled}, tail={self.tail}, "
                f"force={self.force})")

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.sampled == other.sampled
                and self.tail == other.tail
                and self.force == other.force)


def from_header(header: str) -> Optional[TraceContext]:
    """Parse a traceparent header; tolerant — anything malformed is
    ``None`` (absent), never an error."""
    if not header:
        return None
    m = _HEADER_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # the spec's all-zero ids are invalid
    try:
        bits = int(flags, 16)
    except ValueError:
        return None
    return TraceContext(trace_id, span_id, bool(bits & 0x01),
                        tail=bool(bits & 0x02), force=bool(bits & 0x04))


def new_root(sampled: Optional[bool] = None) -> TraceContext:
    """Start a new trace. The head-based sampling decision happens HERE
    and only here — every downstream hop inherits the flag. Under tail
    mode (obs/tail.py) the decision MOVES to root close instead: the root
    carries the tail-pending bit, spans record into the pending buffer,
    and the retention policy rules when the root span closes. A
    force-retain block (``tail.forced()``) records durably at once."""
    if get_force_retain():
        return TraceContext(new_trace_id(), new_span_id(), True, force=True)
    if _tail_mode:
        return TraceContext(new_trace_id(), new_span_id(), False, tail=True)
    if sampled is None:
        rate = _sample_rate
        sampled = rate >= 1.0 or (rate > 0.0 and _rng.random() < rate)
    return TraceContext(new_trace_id(), new_span_id(), sampled)


def current() -> Optional[TraceContext]:
    """The thread's active context (None outside any traced flow)."""
    return getattr(_local, "ctx", None)


def _set(ctx: Optional[TraceContext]) -> None:
    _local.ctx = ctx


class use:
    """``with context.use(ctx): ...`` — activate ``ctx`` on this thread
    for the block. ``use(None)`` is a no-op (so call sites need no branch).
    Plain class, not a generator: this sits on the per-RPC hot path."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx

    def __enter__(self):
        if self.ctx is not None:
            self._prev = getattr(_local, "ctx", None)
            _local.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        if self.ctx is not None:
            _local.ctx = self._prev
        return False


# ---------------------------------------------------------------------------
# wire key injection — context through the existing framing, zero layout
# change
# ---------------------------------------------------------------------------

def inject_key(key: str, ctx: Optional[TraceContext]) -> str:
    """Append ``ctx`` to a frame's key field (``key\\x1fheader``). With no
    context — or with ``MXNET_OBS_WIRE=0`` — the key goes out untouched,
    byte-identical to the old wire format."""
    if ctx is None or not _WIRE:
        return key
    return key + CTX_SEP + ctx.to_header()


def extract_key(key: str) -> Tuple[str, Optional[TraceContext]]:
    """Split a received key into ``(clean_key, ctx_or_None)``. Servers call
    this FIRST, before any key lookup — so a context-stripping server and
    an old-format client are the same code path (no separator → no
    context)."""
    i = key.find(CTX_SEP)
    if i < 0:
        return key, None
    return key[:i], from_header(key[i + 1:])
