"""KVStore package (reference src/kvstore + python/mxnet/kvstore.py)."""
from .kvstore import KVStore, create  # noqa: F401

__all__ = ["KVStore", "create"]
