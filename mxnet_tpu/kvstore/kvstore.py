"""KVStore — gradient aggregation + parameter distribution.

Reference: ``src/kvstore/*`` + ``python/mxnet/kvstore.py`` (TBV — SURVEY.md
§2.1 L7, §2.4, §5.8): modes local/device (intra-node comm), nccl (grouped
allreduce), dist_sync / dist_async / dist_sync_device (ps-lite PS).

TPU-native redesign (SURVEY.md §2.4 table):

- ``local`` / ``device`` / ``nccl`` / ``ici``: single-process modes. With one
  logical array per parameter there is nothing to reduce **between** python
  copies — multi-chip data-parallel runs INSIDE the jitted step as an XLA
  ``psum`` over the Mesh (see mxnet_tpu.parallel). These modes therefore keep
  reference push/pull *semantics* (aggregation of multiple pushed values per
  key, server-side optimizer via set_optimizer) so reference-style training
  loops and the known-value push/pull tests work unchanged.
- ``dist_sync`` / ``dist_async``: multi-process over ``jax.distributed`` /
  a host-side ZMQ parameter server (mxnet_tpu.kvstore.dist).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["KVStore", "create"]


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class KVStore:
    """Single-process key-value store (modes: local, device, nccl, ici)."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store: Dict = {}
        self._updater = None
        self._optimizer = None
        self._compression = None

    # -- identity ---------------------------------------------------------
    @property
    def type(self):
        return self._kind

    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1

    # -- core API ---------------------------------------------------------
    def init(self, key, value):
        keys, values = _as_list(key), _as_list(value)
        for k, v in zip(keys, values):
            k = str(k)
            if k in self._store:
                continue
            self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        """Aggregate value(s) into the key (sum over pushed values, matching
        the reference's merge semantics); if an optimizer is set, run the
        update instead (update_on_kvstore mode).  A multi-key push with a
        server-side optimizer applies the whole batch as ONE fused program
        (optimizer/fused.py) — the local-update analog of the reference's
        grouped server kernels."""
        keys, values = _as_list(key), _as_list(value)
        if len(keys) == 1 and len(values) > 1:
            keys = keys * len(values)
        batch = []  # (key, merged gradient) pairs bound for the updater
        for k, v in zip(keys, values):
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized in kvstore")
            vs = _as_list(v)
            merged = vs[0]
            for extra in vs[1:]:
                merged = merged + extra
            # DistKVStore keeps the raw params dict in _compression and does
            # its own wire-level compression; only the device-kvstore path
            # stores a GradientCompression here
            comp = self._compression
            if comp is not None and hasattr(comp, "compress"):
                import numpy as _np

                g = merged.asnumpy().astype(_np.float32)
                packed = comp.compress(k, g)
                merged = type(merged)(comp.decompress(packed, g.shape))
            if self._updater is not None:
                batch.append((k, merged))
            else:
                self._pending = getattr(self, "_pending", {})
                self._pending.setdefault(k, []).append(merged)
        if len(batch) > 1 and hasattr(self._updater, "update_batch"):
            idxs = [int(k) if k.isdigit() else k for k, _ in batch]
            self._updater.update_batch(idxs, [m for _, m in batch],
                                       [self._store[k] for k, _ in batch])
        else:
            for k, merged in batch:
                self._updater(int(k) if k.isdigit() else k, merged,
                              self._store[k])

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = _as_list(key)
        outs = _as_list(out)
        if len(keys) == 1 and len(outs) > 1:
            keys = keys * len(outs)
        for k, o in zip(keys, outs):
            k = str(k)
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized in kvstore")
            self._flush(k)
            for oo in _as_list(o):
                oo._set_data(self._store[k]._data)

    def _flush(self, k):
        pending = getattr(self, "_pending", {}).pop(k, None)
        if pending:
            merged = pending[0]
            for extra in pending[1:]:
                merged = merged + extra
            self._store[k]._set_data(merged._data)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out=out if out is not None else value, priority=priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows named by row_ids (reference sparse embedding
        path). Dense emulation: gather rows."""
        keys = _as_list(key)
        outs = _as_list(out)
        rids = _as_list(row_ids)
        for k, o, r in zip(keys, outs, rids):
            k = str(k)
            self._flush(k)
            full = self._store[k]
            rows = full.take(r.astype("int32") if hasattr(r, "astype") else r)
            o._set_data(rows._data)

    # -- optimizer-on-store ----------------------------------------------
    def set_optimizer(self, optimizer):
        from ..optimizer import Updater

        self._optimizer = optimizer
        self._updater = Updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        # reference contract (kvstore.py set_gradient_compression): device
        # and dist kvstores accept compression; cpu-only 'local' rejects it.
        from .compression import GradientCompression, validate_compression_params

        params = validate_compression_params(compression_params)
        if params is None:
            self._compression = None
            return
        if self._kind not in ("device", "ici", "nccl"):
            raise MXNetError(
                f"gradient compression is not supported for kvstore type "
                f"{self._kind!r}; use device or dist_sync/dist_async")
        # single-process semantics: quantize+dequantize each pushed gradient
        # (with error feedback) so numerics match the dist wire format —
        # there is no bandwidth to save inside one process
        self._compression = GradientCompression(params["threshold"])

    # -- persistence / misc ----------------------------------------------
    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        pass

    def _barrier(self):
        pass


def create(name="local") -> KVStore:
    """Create a kvstore (reference kvstore.create). Modes:

    local/device/nccl/ici → single-process KVStore (multi-chip DP is an XLA
    psum inside the step); dist_sync/dist_device_sync → multi-process
    DistKVStore over jax.distributed collectives; dist_async → ZMQ PS client.
    """
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device", "nccl", "ici"):
        return KVStore(name)
    if name in ("dist_sync", "dist_device_sync", "dist_sync_device", "dist_async",
                "dist"):
        from .dist import DistKVStore

        return DistKVStore(name)
    if name == "horovod":
        raise MXNetError("horovod kvstore is not applicable on TPU; use dist_sync")
    raise MXNetError(f"unknown kvstore type {name!r}")
