"""2-bit gradient compression with error feedback.

Reference: ``src/kvstore/gradient_compression.cc`` (expected path per
SURVEY.md §2.4 — mount empty this round). Semantics reproduced:

- Each f32 gradient value quantizes to 2 bits against a threshold:
  ``01`` if residual >= threshold, ``10`` if residual <= -threshold, ``00``
  otherwise (4 values per byte, little-endian within the byte).
- Error feedback: the worker keeps a per-key residual; each round
  ``residual += grad``, the quantized value ``±threshold`` is sent, and the
  sent amount is subtracted from the residual — no gradient mass is ever
  dropped, only delayed.

Wire format (shared with the PS servers — python twin and
native/ps/ps_server.cc): dtype code ``16`` in the standard array framing,
payload = ``f32 threshold | packed bytes``. 16× smaller on the wire than f32
for large tensors.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..base import MXNetError

TWO_BIT_DTYPE_CODE = 16

__all__ = ["GradientCompression", "TWO_BIT_DTYPE_CODE",
           "quantize_2bit", "dequantize_2bit", "validate_compression_params"]


def validate_compression_params(params) -> Optional[dict]:
    """Reference kvstore.set_gradient_compression contract: type in
    {'none', '2bit'}, threshold > 0. Anything else must raise, not no-op."""
    if params is None:
        return None
    params = dict(params)
    ctype = params.pop("type", None)
    if ctype in (None, "none"):
        if params:
            raise MXNetError(f"unexpected compression params {params}")
        return None
    if ctype != "2bit":
        raise MXNetError(
            f"gradient compression type {ctype!r} is not supported "
            "(supported: '2bit')")
    threshold = float(params.pop("threshold", 0.5))
    if threshold <= 0:
        raise MXNetError("threshold must be > 0")
    if params:
        raise MXNetError(f"unexpected compression params {params}")
    return {"type": "2bit", "threshold": threshold}


def quantize_2bit(residual: np.ndarray, threshold: float):
    """Quantize `residual` in place: returns packed uint8 codes and subtracts
    the transmitted amount from `residual` (error feedback)."""
    pos = residual >= threshold
    neg = residual <= -threshold
    codes = np.where(pos, np.uint8(1), np.where(neg, np.uint8(2), np.uint8(0)))
    codes = codes.astype(np.uint8).ravel()
    residual -= threshold * (pos.astype(np.float32) - neg.astype(np.float32))
    pad = (-len(codes)) % 4
    if pad:
        codes = np.concatenate([codes, np.zeros(pad, np.uint8)])
    c = codes.reshape(-1, 4)
    packed = (c[:, 0] | (c[:, 1] << 2) | (c[:, 2] << 4) | (c[:, 3] << 6))
    return packed.astype(np.uint8)


def dequantize_2bit(packed: np.ndarray, threshold: float, size: int,
                    dtype=np.float32) -> np.ndarray:
    """Unpack 2-bit codes back to ±threshold / 0 floats (flat, length=size)."""
    p = packed.astype(np.uint8)
    codes = np.empty((len(p), 4), np.uint8)
    codes[:, 0] = p & 3
    codes[:, 1] = (p >> 2) & 3
    codes[:, 2] = (p >> 4) & 3
    codes[:, 3] = (p >> 6) & 3
    flat = codes.ravel()[:size]
    out = np.zeros(size, dtype)
    out[flat == 1] = threshold
    out[flat == 2] = -threshold
    return out


class GradientCompression:
    """Worker-side state: residuals per key + pack/unpack helpers."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = float(threshold)
        self._residuals: Dict[str, np.ndarray] = {}

    def compress(self, key: str, grad: np.ndarray) -> np.ndarray:
        res = self._residuals.get(key)
        if res is None or res.shape != grad.shape:
            res = self._residuals[key] = np.zeros(grad.shape, np.float32)
        res += grad.astype(np.float32)
        return quantize_2bit(res, self.threshold)

    def decompress(self, packed: np.ndarray, shape) -> np.ndarray:
        size = int(np.prod(shape)) if len(shape) else 1
        return dequantize_2bit(packed, self.threshold, size).reshape(shape)

    def pack_wire(self, key: str, grad: np.ndarray) -> bytes:
        """Array framing payload with dtype code 16 (see module docstring)."""
        import struct

        packed = self.compress(key, grad)
        head = struct.pack("<B", grad.ndim) \
            + struct.pack(f"<{grad.ndim}I", *grad.shape) \
            + struct.pack("<B", TWO_BIT_DTYPE_CODE) \
            + struct.pack("<f", self.threshold)
        return head + packed.tobytes()
