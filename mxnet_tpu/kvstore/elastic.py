"""Elastic training plane: worker membership, generations, survivable sync.

The reference's distributed identity is ps-lite, whose design premise is
surviving flaky peers (PAPER.md §1) — yet a classic ``dist_sync`` run dies
with its weakest worker: one SIGKILL wedges every barrier until a blanket
timeout. This module gives the *training* plane the same supervision story
PR 6 gave serving (``serve/fleet.py``):

- **Membership** (:class:`ElasticState`, server side): workers announce
  themselves (``OP_JOIN``) and heartbeat (``OP_HB``) on the existing PS
  wire framing. A liveness monitor declares a worker dead after K missed
  heartbeats and bumps a monotonically increasing **generation** number;
  every barrier, reduction round, and epoch rendezvous is scoped to the
  live membership, so a dead rank *releases* collective waits over the
  survivors instead of timing them out.
- **Generation-scoped sync reduction** (``OP_REDUCE``): the allreduce
  transport for elastic ``dist_sync`` — workers contribute one array per
  round; the round completes when every *live* member contributed (a
  mid-round death shrinks the requirement). Contributions are deduped by
  client id and completed rounds are LRU-cached, so a retried frame whose
  ack was lost is answered idempotently (the ``(client_id, seq)`` push
  idiom from PR 2).
- **Epoch rendezvous** (``OP_EPOCH``): a generation-scoped barrier at
  epoch boundaries where membership changes are *applied*: quarantined
  joiners are activated, the data-shard assignment (``part_index`` /
  ``num_parts`` over ranks) is recut, and reduce-round numbering resets.
  A worker that restarts mid-epoch is **quarantined** until the next
  boundary (the fleet resync idiom from ``serve/fleet.py`` applied to
  training ranks) and meanwhile restores weights/optimizer/RNG from the
  shared PR-2 checkpoint directory — the checkpointed rejoin.
- **Worker session** (:class:`ElasticWorkerSession`, client side): join /
  await-activation / allreduce / epoch_end plus a background
  :class:`Heartbeater` on its own socket.

PS state durability (server snapshots through ``checkpoint/``'s atomic+CRC
machinery, warm restart with the seq-dedup table intact) lives in
``ps_server.py``; the capture/install helpers are here (:func:`capture_server_state`
/ :func:`install_server_state`).

Env knobs (registered in ``mxnet_tpu/runtime.py``): ``MXNET_ELASTIC``,
``MXNET_ELASTIC_HEARTBEAT_S``, ``MXNET_ELASTIC_MISS_K``,
``MXNET_ELASTIC_JOIN_TIMEOUT_S``, ``MXNET_ELASTIC_REDUCE_TIMEOUT_S``.
"""
from __future__ import annotations

import struct
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

import numpy as np

from .. import obs, tsan
from ..base import MXNetError, get_env
from ..wire import PS_WIRE

__all__ = ["ElasticState", "ElasticWorkerSession", "Heartbeater", "JoinInfo",
           "ElasticError", "StaleMemberError", "elastic_enabled",
           "heartbeat_interval", "miss_threshold", "capture_server_state",
           "install_server_state", "ELASTIC_OP_NAMES"]

# Opcodes 16-26: the elastic-training range on the PS wire (0-9 = kvstore,
# 32-43 = serve — same framing). Codes come from the declarative registry
# (mxnet_tpu/wire.py), where collisions are impossible by construction.
(OP_HB, OP_JOIN, OP_REDUCE, OP_EPOCH, OP_LEAVE, OP_CLOCK, OP_CLOCK_PULL,
 OP_PULL_STALE, OP_REDUCE_SCOPED) = PS_WIRE.codes(
    "heartbeat", "join", "reduce", "epoch", "leave", "clock", "clock_pull",
    "pull_stale", "reduce_scoped")

ELASTIC_OP_NAMES = {code: name for code, name in PS_WIRE.names().items()
                    if code in (OP_HB, OP_JOIN, OP_REDUCE, OP_EPOCH,
                                OP_LEAVE, OP_CLOCK, OP_CLOCK_PULL,
                                OP_PULL_STALE, OP_REDUCE_SCOPED)}

# OP_EPOCH payload carrying this epoch value means "block until my
# quarantined membership is activated" (the rejoin wait).
WAIT_ACTIVATION = (1 << 64) - 1

# member status codes on the wire
ST_OK, ST_ERROR, ST_QUARANTINED, ST_STALE = 0, 1, 2, 3


class ElasticError(MXNetError):
    """An elastic-plane RPC failed structurally (timeout / protocol)."""


class StaleMemberError(ElasticError):
    """The server no longer counts this worker as a live member — it was
    declared dead (missed heartbeats) or never activated. The worker must
    re-join at the next epoch boundary; continuing to push would mix a
    stale generation into the live fleet's reductions."""


def elastic_enabled() -> bool:
    return bool(get_env("MXNET_ELASTIC", False, bool))


def heartbeat_interval() -> float:
    return float(get_env("MXNET_ELASTIC_HEARTBEAT_S", 0.5, float))


def miss_threshold() -> int:
    return int(get_env("MXNET_ELASTIC_MISS_K", 4, int))


def _join_timeout() -> float:
    return float(get_env("MXNET_ELASTIC_JOIN_TIMEOUT_S", 600.0, float))


def _reduce_timeout() -> float:
    return float(get_env("MXNET_ELASTIC_REDUCE_TIMEOUT_S", 120.0, float))


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------

class _Member:
    __slots__ = ("cid", "rank", "state", "last_hb", "joined_gen")

    def __init__(self, cid: int, rank: int, state: str, gen: int):
        self.cid = cid
        self.rank = rank
        self.state = state  # active | quarantined | dead
        self.last_hb = time.monotonic()
        self.joined_gen = gen


class _Round:
    __slots__ = ("contribs", "stamps", "expected")

    def __init__(self):
        self.contribs: Dict[int, np.ndarray] = {}
        # cid -> arrival monotonic: the reduce wait-by-rank attribution
        # (who stood waiting vs who arrived last) reads these at release
        self.stamps: Dict[int, float] = {}
        # scoped rounds (OP_REDUCE_SCOPED, the hierarchical-reduction
        # transport) complete at this contributor count instead of the
        # full live membership; 0 = membership-scoped (classic)
        self.expected = 0


class ElasticState:
    """Server-side membership + generation-scoped collectives.

    One Condition guards everything; collective waits (reduce rounds, epoch
    rendezvous) re-evaluate their completion condition on every wake, so a
    membership change (death, activation) *releases* them over the
    surviving set instead of leaving them to time out.
    """

    def __init__(self, hb_interval: Optional[float] = None,
                 miss_k: Optional[int] = None, on_change=None,
                 on_prune=None):
        self.cv = tsan.condition("elastic.state.cv")
        self.members: Dict[int, _Member] = {}
        self.generation = 0
        self.epoch = 0  # the epoch currently in progress fleet-wide
        self.started = False  # any reduce/epoch seen → later joins quarantine
        self.hb_interval = (heartbeat_interval() if hb_interval is None
                            else float(hb_interval))
        self.miss_k = miss_threshold() if miss_k is None else int(miss_k)
        self._rounds: Dict = {}        # (key, round) -> _Round
        self._completed: "OrderedDict" = OrderedDict()  # LRU: retried rounds
        self._epoch_arrived: set = set()
        # shard-recut rotation (the on_straggler data_wait actuation):
        # each request_recut() bumps the salt, rotating part indices over
        # the rank order at the NEXT epoch boundary — a pathological shard
        # (cold cache, slow storage segment) moves off the blamed rank
        self.shard_salt = 0
        self._last_release: Optional[dict] = None
        # callbacks poked (outside cv) after any membership change — the
        # PSServer hangs its barrier-release re-check here
        self._on_change = list(on_change or [])
        # callbacks fired (outside cv) with each PRUNED/LEFT cid — the
        # PSServer's fleet-telemetry cache drops that member's parts
        self._on_prune = list(on_prune or [])
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # -- views (call with cv held unless noted) -------------------------
    def has_members(self) -> bool:
        return bool(self.members)

    def active_members(self):
        return [m for m in self.members.values() if m.state == "active"]

    def active_count(self) -> int:
        return len(self.active_members())

    def assignment(self, cid: int):
        """(part_index, num_parts) over actives ordered by (rank, cid),
        rotated by the recut salt (every worker applies the new cut at
        the next epoch boundary — ``epoch_end`` reports it as changed)."""
        order = sorted(self.active_members(), key=lambda m: (m.rank, m.cid))
        n = len(order)
        for i, m in enumerate(order):
            if m.cid == cid:
                return (i + self.shard_salt) % n, n
        return 0, max(1, n)

    def request_recut(self) -> int:
        """Rotate the shard assignment at the next epoch boundary (the
        on_straggler data_wait actuation — see docs/ROBUSTNESS.md
        "Asynchronous training"). Returns the new salt."""
        with self.cv:
            self.shard_salt += 1
            salt = self.shard_salt
        obs.inc("elastic.shard_recuts")
        obs.event("elastic.shard_recut_requested", salt=salt)
        return salt

    def liveness_table(self):
        """[(rank, cid, state, heartbeat_age_s)] — the structured
        barrier-timeout report and STATS both read this."""
        now = time.monotonic()
        with self.cv:
            return [(m.rank, m.cid, m.state, round(now - m.last_hb, 3))
                    for m in self.members.values()]

    # -- membership -----------------------------------------------------
    def join(self, cid: int, rank: int):
        with self.cv:
            m = self.members.get(cid)
            if m is None:
                state = "quarantined" if self.started else "active"
                m = _Member(cid, rank, state, self.generation)
                self.members[cid] = m
                if state == "active":
                    self._bump_generation("join", cid=cid, rank=rank)
                obs.inc("elastic.joins")
                obs.event("elastic.member_joined", cid=cid, rank=rank,
                          state=state, generation=self.generation)
            elif m.state != "dead":
                # same guard as heartbeat(): a declared-dead cid's join
                # retries must not refresh last_hb — that would pin the
                # corpse past the prune GC and lock the cid out forever
                # (post-prune, a fresh join re-registers it cleanly)
                m.last_hb = time.monotonic()
            part, nparts = self.assignment(cid)
            reply = (m.state, self.generation, self.epoch, part, nparts,
                     self.active_count())
        self._ensure_monitor()
        self._notify_change()
        return reply

    def heartbeat(self, cid: int):
        with self.cv:
            m = self.members.get(cid)
            if m is None:
                return ST_ERROR, self.generation, self.active_count()
            if m.state != "dead":
                # a DEAD member's beats must not refresh last_hb: a zombie
                # that keeps heartbeating would otherwise defeat the
                # liveness loop's prune_after GC forever
                m.last_hb = time.monotonic()
            st = ST_OK if m.state == "active" else (
                ST_QUARANTINED if m.state == "quarantined" else ST_STALE)
            return st, self.generation, self.active_count()

    def leave(self, cid: int):
        with self.cv:
            m = self.members.pop(cid, None)
            if m is not None and m.state == "active":
                self._bump_generation("leave", cid=cid, rank=m.rank)
                if not self.active_members():
                    # fleet takeover (same rule as the all-dead case): a
                    # joiner quarantined behind a fleet that has finished
                    # and left would otherwise wait for a boundary nobody
                    # can ever reach
                    self._takeover_locked()
                self._reevaluate_locked()
        if m is not None:
            self._forget_member(cid, pruned=False)
        self._notify_change()

    def _bump_generation(self, reason: str, **attrs):
        """Caller holds cv."""
        self.generation += 1
        obs.set_gauge("elastic.generation", self.generation)
        obs.set_gauge("elastic.active_workers", self.active_count())
        obs.event("elastic.generation_bump", reason=reason,
                  generation=self.generation, **attrs)

    # -- liveness monitor ------------------------------------------------
    def _ensure_monitor(self):
        if self._monitor is None or not self._monitor.is_alive():
            self._monitor = threading.Thread(
                target=self._liveness_loop, daemon=True,
                name="mxtpu-elastic-liveness")
            self._monitor.start()

    def close(self):
        self._stop.set()
        with self.cv:
            self.cv.notify_all()
        if self._monitor is not None:
            self._monitor.join(timeout=2)
            if self._monitor.is_alive():
                obs.inc("elastic.monitor_thread_leaked")

    def _liveness_loop(self):
        window = self.hb_interval * self.miss_k
        # corpses are pruned once they can no longer matter: a restarted
        # worker draws a FRESH cid, so dead entries only accumulate with
        # churn (the seq-dedup table is LRU-bounded for the same reason) —
        # a pruned zombie's request gets the same ST_STALE as a dead one
        prune_after = max(30.0, window * 10)
        while not self._stop.wait(self.hb_interval):
            now = time.monotonic()
            changed = False
            pruned = []
            rec = obs.enabled()
            with self.cv:
                for m in list(self.members.values()):
                    if m.state in ("active", "quarantined") \
                            and now - m.last_hb > window:
                        m.state = "dead"
                        changed = True
                        obs.inc("elastic.deaths")
                        obs.event("elastic.member_dead", cid=m.cid,
                                  rank=m.rank,
                                  heartbeat_age_s=round(now - m.last_hb, 3))
                    elif m.state == "dead" \
                            and now - m.last_hb > prune_after:
                        del self.members[m.cid]
                        pruned.append(m.cid)
                if rec:
                    # membership liveness as gauges, refreshed per sweep
                    # (the exposition's fleet-health row; pruned members'
                    # gauges are removed below, never frozen)
                    for m in self.members.values():
                        obs.set_gauge(
                            f"kvstore.member{m.cid}.last_hb_age_s",
                            round(now - m.last_hb, 3))
                    obs.set_gauge("kvstore.generation", self.generation)
                    obs.set_gauge("kvstore.live_workers",
                                  self.active_count())
                if changed:
                    self._bump_generation("death")
                    # fleet takeover: every active died while joiners wait
                    # quarantined — activate them or they wait forever for
                    # a boundary nobody can reach
                    if not self.active_members():
                        self._takeover_locked()
                    self._reevaluate_locked()
            for cid in pruned:
                self._forget_member(cid)
            if changed:
                self._notify_change()

    def _notify_change(self):
        for cb in self._on_change:
            try:
                cb()
            except Exception:  # noqa: BLE001 — observer must not kill liveness
                pass

    def _forget_member(self, cid: int, pruned: bool = True) -> None:
        """A member left the table for good (prune GC or LEAVE): drop its
        per-member gauge from the exposition — a removed worker must not
        sit there forever as a frozen last value. Only a PRUNE (a corpse
        GC'd long after death) additionally tells the prune observers to
        drop cached state: a clean LEAVE keeps the member's fleet
        telemetry — its step attribution is exactly what a post-run
        train_report pulls — and the caches are LRU-bounded regardless."""
        from ..obs import metrics as _metrics

        _metrics.remove(f"kvstore.member{cid}.last_hb_age_s")
        if not pruned:
            return
        for cb in self._on_prune:
            try:
                cb(cid)
            except Exception:  # noqa: BLE001 — observer must not kill liveness
                pass

    def _reevaluate_locked(self):
        """Membership shrank: any collective wait may now be complete."""
        for ck in list(self._rounds):
            self._try_complete_round_locked(ck)
        self._try_release_boundary_locked()
        self.cv.notify_all()

    # -- generation-scoped reduce ---------------------------------------
    def reduce(self, cid: int, key: str, round_id: int, arr: np.ndarray,
               timeout: float, expected: int = 0):
        """Blocking sum-allreduce contribution. Returns
        ``(status, generation, contributors, result)``.

        ``expected > 0`` makes this a *scoped* round (hierarchical
        reduction): it completes once that many distinct cids contributed
        — the server does not know group membership, so a death inside
        the group is covered by the caller's timeout + flat fallback
        (deaths that shrink the whole fleet below ``expected`` still
        release the round over the survivors)."""
        with self.cv:
            self.started = True
            m = self.members.get(cid)
            if m is None or m.state != "active":
                obs.inc("elastic.stale_rejected")
                return ST_STALE, self.generation, 0, None
            ck = (key, int(round_id))
            done = self._completed.get(ck)
            if done is not None:  # idempotent retry of a released round
                return ST_OK, self.generation, done[0], done[1]
            r = self._rounds.setdefault(ck, _Round())
            if expected:
                r.expected = max(r.expected, int(expected))
            if cid not in r.contribs:
                r.stamps[cid] = time.monotonic()
            r.contribs.setdefault(cid, arr)  # dedup a duplicated frame
            self._try_complete_round_locked(ck)
            deadline = time.monotonic() + timeout
            while ck not in self._completed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return ST_ERROR, self.generation, 0, None
                self.cv.wait(timeout=min(remaining, self.hb_interval))
                self._try_complete_round_locked(ck)
            done = self._completed[ck]
            return ST_OK, self.generation, done[0], done[1]

    def _try_complete_round_locked(self, ck):
        r = self._rounds.get(ck)
        if r is None:
            return
        required = {m.cid for m in self.active_members()}
        if r.expected:
            # scoped round: N distinct contributors release it, capped at
            # the live fleet size so deaths can still release the round
            need = min(r.expected, len(required)) if required \
                else r.expected
            if len(r.contribs) < max(1, need):
                return
        elif not required or not required.issubset(r.contribs):
            return
        contribs = list(r.contribs.values())
        result = contribs[0].copy()
        for c in contribs[1:]:
            result += c
        n = len(contribs)  # contributions from since-dead members included
        self._completed[ck] = (n, result)
        # a released round can only be retried by a client still ON it, and
        # clients advance one round past their own success — so per key
        # only the last two rounds are reachable. Each cached result is a
        # full flattened gradient vector; keeping 64 of them would pin
        # ~64x model size on the server
        key = ck[0]
        for stale in [c for c in self._completed
                      if c[0] == key and c[1] < ck[1] - 1]:
            del self._completed[stale]
        while len(self._completed) > 64:
            self._completed.popitem(last=False)
        del self._rounds[ck]
        obs.inc("elastic.reduce_rounds")
        if obs.enabled() and r.stamps:
            # reduce wait-by-rank: each contributor's wait is "round
            # release minus its arrival" — the rank with ~zero wait
            # arrived last and is what the fleet stood waiting on. The
            # per-rank histograms corroborate the StragglerDetector's
            # blame from the server's own vantage point.
            now = time.monotonic()
            last_cid = max(r.stamps, key=lambda c: r.stamps[c])
            for cid_, t0 in r.stamps.items():
                m = self.members.get(cid_)
                if m is None:
                    continue
                obs.observe(f"kvstore.reduce_wait.rank{m.rank}_seconds",
                            now - t0)
            m = self.members.get(last_cid)
            if m is not None:
                obs.inc(f"kvstore.reduce_last_arriver.rank{m.rank}")
        if not r.expected and set(r.contribs) != required:
            # released over a different set than required right now — a
            # member died mid-round (its gradient, if sent, still counts);
            # scoped rounds complete under the full membership by design
            obs.inc("elastic.reduce_partial")
        self.cv.notify_all()

    # -- epoch rendezvous ------------------------------------------------
    def epoch_end(self, cid: int, epoch: int, timeout: float):
        """Generation-scoped boundary barrier. Returns
        ``(status, generation, next_epoch, part, nparts, active_count)``.

        ``epoch == WAIT_ACTIVATION`` is the quarantined-rejoin wait: block
        until this member is activated at a boundary, then report the same
        release the actives saw.
        """
        deadline = time.monotonic() + timeout
        with self.cv:
            m = self.members.get(cid)
            if m is None or m.state == "dead":
                return (ST_STALE, self.generation, self.epoch, 0, 1,
                        self.active_count())
            if epoch == WAIT_ACTIVATION:
                while m.state == "quarantined":
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return (ST_ERROR, self.generation, self.epoch, 0, 1,
                                self.active_count())
                    self.cv.wait(timeout=min(remaining, self.hb_interval))
                    m = self.members.get(cid)
                    if m is None or m.state == "dead":
                        return (ST_STALE, self.generation, self.epoch, 0, 1,
                                self.active_count())
                return self._release_reply_locked(cid)
            self.started = True
            if m.state != "active":
                return (ST_STALE, self.generation, self.epoch, 0, 1,
                        self.active_count())
            if epoch > self.epoch:
                # server behind the fleet (restarted without — or with a
                # stale — snapshot while workers resumed from shared
                # checkpoints): the FLEET's epoch is authoritative. Without
                # this jump, the first release would clear the arrivals and
                # every worker would wait out the full join timeout for a
                # boundary count that can never re-form. The jump is a
                # boundary resync, so it clears the collective tables like
                # a release — a lower-epoch waiter woken by it exits as
                # "already released" and must NOT then find pre-jump
                # cached rounds answering its restarted round numbers.
                self.epoch = int(epoch)
                self._epoch_arrived.clear()
                self._rounds.clear()
                self._completed.clear()
                self.cv.notify_all()
            if epoch < self.epoch:  # retry of an already-released boundary
                return self._release_reply_locked(cid)
            self._epoch_arrived.add(cid)
            self._try_release_boundary_locked()
            while self.epoch <= epoch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._epoch_arrived.discard(cid)
                    return (ST_ERROR, self.generation, self.epoch, 0, 1,
                            self.active_count())
                self.cv.wait(timeout=min(remaining, self.hb_interval))
                if cid not in self.members \
                        or self.members[cid].state == "dead":
                    return (ST_STALE, self.generation, self.epoch, 0, 1,
                            self.active_count())
                self._try_release_boundary_locked()
            return self._release_reply_locked(cid)

    def _try_release_boundary_locked(self):
        required = {m.cid for m in self.active_members()}
        if not required or not required.issubset(self._epoch_arrived):
            return
        activated = self._activate_quarantined_locked()
        self._epoch_arrived.clear()
        # reduce rounds are scoped to the epoch: the boundary is a true
        # barrier (no reduce can be in flight past it), so clearing the
        # tables lets round numbering restart at 0 — which is also how a
        # rejoiner syncs its counter without any extra protocol
        self._rounds.clear()
        self._completed.clear()
        self.epoch += 1
        self._last_release = {"generation": self.generation,
                              "epoch": self.epoch}
        obs.event("elastic.epoch_released", epoch=self.epoch,
                  generation=self.generation, activated=activated,
                  active=self.active_count())
        self.cv.notify_all()

    def _takeover_locked(self) -> int:
        """Every active member died/left: quarantined joiners become the
        fleet. The dead fleet's round tables MUST be cleared exactly like
        a boundary release — the joiners restart round numbering at 0, and
        a cached released round from the old fleet answering their round 0
        would hand back a stale gradient sum."""
        activated = self._activate_quarantined_locked()
        if activated:
            self._rounds.clear()
            self._completed.clear()
            self._epoch_arrived.clear()
        return activated

    def _activate_quarantined_locked(self) -> int:
        joiners = [m for m in self.members.values()
                   if m.state == "quarantined"]
        for m in joiners:
            m.state = "active"
            obs.inc("elastic.rejoins")
            obs.event("elastic.member_activated", cid=m.cid, rank=m.rank)
        if joiners:
            self._bump_generation("activate",
                                  ranks=[m.rank for m in joiners])
        return len(joiners)

    def _release_reply_locked(self, cid):
        part, nparts = self.assignment(cid)
        return (ST_OK, self.generation, self.epoch, part, nparts,
                self.active_count())


# ---------------------------------------------------------------------------
# PS state durability (weights + optimizer + seq-dedup + generation)
# ---------------------------------------------------------------------------

def capture_server_state(server):
    """Consistent snapshot of a PSServer for the durable-warm-restart path.

    Per-key consistency: each key's weight, its optimizer slot, and its
    seq-dedup entries are copied while holding *that key's* lock — the same
    lock ``OP_PUSH_SEQ`` applies+records under — so a snapshot can never
    contain an applied update without its seq (the double-apply hole) for
    any single key. Cross-key skew is harmless: exactly-once is a per-key
    invariant.
    """
    from ..checkpoint.state import (TrainingState, _flatten_opt_state,
                                    capture_optimizer)

    arrays: Dict[str, np.ndarray] = {}
    seq_entries = []
    opt_tree = []
    updater = server._updater
    with server._global_lock:
        keys = list(server._weights)
    for key in keys:
        lock = server._locks.get(key, server._global_lock)
        with lock:
            w = server._weights.get(key)
            if w is None:
                continue
            arrays[f"w:{key}"] = np.ascontiguousarray(w)
            with server._seq_lock:
                for cid, seq in server._seq_by_key.get(key, {}).items():
                    seq_entries.append([str(cid), key, int(seq)])
            if updater is not None and key in updater.states:
                deferred: list = []
                desc = _flatten_opt_state(updater.states[key], key, deferred)
                # host-copy NOW, inside the key lock — a deferred batched
                # transfer would read slots the next push already mutated
                for dkey, val in deferred:
                    host = val.asnumpy() if hasattr(val, "asnumpy") \
                        else np.asarray(val)
                    arrays[dkey] = np.ascontiguousarray(host)
                opt_tree.append(["s", key, desc])
    meta = {
        "kind": "ps_server",
        "opt_spec": server._opt_spec,
        "optimizer": {"state_tree": opt_tree},
        "seq": seq_entries,
        "num_workers": server._num_workers,
    }
    # bounded-staleness async: the committed-clock table rides the
    # snapshot (and kind-4 WAL records cover advances after it) so a
    # SIGKILL mid-async-storm restarts with the staleness gate's view of
    # the fleet intact — a zeroed clock floor would wrongly admit every
    # fast rank an extra `s` steps ahead
    with server._clock_cv:
        if server._clock:
            meta["clock"] = [[int(r), int(c)]
                             for r, c in server._clock.items()]
            meta["clock_cids"] = [[str(cid), int(r)]
                                  for cid, r in server._clock_rank.items()]
    if server._optimizer is not None:
        # scalar counters the slots don't carry (reuse PR-2's capture)
        scal = capture_optimizer(None, server._optimizer, arrays)
        meta["optimizer"].update(
            {k: v for k, v in scal.items() if k != "state_tree"})
    el = server._elastic
    if el is not None:
        with el.cv:
            meta["generation"] = el.generation
            meta["epoch"] = el.epoch
            meta["started"] = el.started
            # membership rides the snapshot so an elastic fleet SURVIVES a
            # PS warm restart: restored members resume heartbeating on
            # their existing sockets and in-flight reduces simply retry
            # (idempotent) against the fresh round tables — without this,
            # every worker's next RPC would be a stale rejection and the
            # restart designed to preserve exactly-once would kill the
            # whole training fleet instead
            meta["members"] = [
                [str(m.cid), int(m.rank), m.state]
                for m in el.members.values() if m.state != "dead"]
    return TrainingState(arrays, meta)


def install_server_state(server, state) -> None:
    """Warm-restart a PSServer from a :func:`capture_server_state` snapshot:
    weights, server optimizer (spec re-parsed, slots + counters restored),
    the seq-dedup table (so replayed pushes from before the crash still
    dedupe — exactly-once survives the restart), and the membership
    generation (monotonic across incarnations)."""
    from ..checkpoint.state import _unflatten_opt_state, restore_optimizer

    for name, arr in state.arrays.items():
        if name.startswith("w:"):
            key = name[2:]
            server._weights[key] = np.array(arr)
            server._locks[key] = tsan.lock("ps.key")
    with server._seq_lock:
        for cid, key, seq in state.meta.get("seq", []):
            server._record_seq(int(cid), key, int(seq))
    with server._clock_cv:
        for rank, clock in state.meta.get("clock", []):
            cur = server._clock.get(int(rank), 0)
            server._clock[int(rank)] = max(cur, int(clock))
        for cid, rank in state.meta.get("clock_cids", []):
            server._clock_rank[int(cid)] = int(rank)
        server._clock_cv.notify_all()
    spec = state.meta.get("opt_spec")
    if spec:
        server._set_optimizer_bytes(spec.encode("ascii"), warm=False)
        meta = state.meta.get("optimizer", {})
        if server._updater is not None:
            server._updater.states = {
                key: _unflatten_opt_state(desc, state.arrays)
                for _tag, key, desc in meta.get("state_tree", [])}
        restore_optimizer(None, server._optimizer, state)
    if server._elastic is not None and "generation" in state.meta:
        el = server._elastic
        with el.cv:
            el.generation = int(state.meta["generation"])
            el.epoch = int(state.meta.get("epoch", 0))
            el.started = bool(state.meta.get("started", False))
            for cid, rank, mstate in state.meta.get("members", []):
                m = _Member(int(cid), int(rank), mstate, el.generation)
                el.members[int(cid)] = m  # fresh last_hb: a grace window
        el._ensure_monitor()  # a stale restored member still gets reaped
    obs.event("elastic.ps_warm_restart",
              keys=len([k for k in state.arrays if k.startswith("w:")]),
              seq_entries=len(state.meta.get("seq", [])),
              generation=state.meta.get("generation"))


class PushWAL:
    """Write-ahead log for seq-tagged pushes (the durability half of
    exactly-once across a server SIGKILL).

    A snapshot alone cannot give "zero lost updates": a push ACKED after
    the last snapshot would vanish with the process — and an acked push is
    one the client will never resend. So every applied seq-push appends a
    CRC-framed record (cid, seq, key, grad payload) here and is fsynced
    BEFORE the ack leaves. Warm restart replays records through the
    ordinary seq-dedup path: anything the snapshot already contains has
    ``seq <= applied_seq[(cid, key)]`` (the per-key snapshot consistency
    guarantee) and is skipped, anything newer re-applies — exactly once,
    mechanically.

    Record framing: ``u32 len | u32 crc32(body) | body`` with
    ``body = u8 kind | u64 cid | u64 seq | u16 klen | key | payload``
    (kind 0 = dense array payload, 1 = sparse (indices, rows) payload,
    2 = key birth from OP_INIT — first-wins on replay, cid/seq unused;
    3 = optimizer spec; 4 = committed-clock advance from OP_CLOCK — the
    key is the decimal rank, seq the step, and replay max-merges, so a
    replayed record can never roll a clock back).
    A torn tail record (SIGKILL mid-append) fails the CRC and truncates
    the replay there — by construction that push was never acked, so the
    client retries it. Files rotate at each snapshot commit
    (``wal-<next-snapshot-step>.bin``) and older ones are GC'd; replay
    walks every surviving file in step order (dedup makes overlap safe).

    ``MXNET_PS_WAL_FSYNC=0`` trades the fsync-per-push for speed (then a
    power loss can drop the tail; a plain SIGKILL usually cannot, since
    the page cache survives the process).
    """

    def __init__(self, directory: str):
        import os

        self._dir = directory
        self._lock = tsan.lock("elastic.wal")
        self._file = None
        self._fsync = bool(get_env("MXNET_PS_WAL_FSYNC", True, bool))
        self._os = os

    def _path(self, step: int) -> str:
        return self._os.path.join(self._dir, f"wal-{step:08d}.bin")

    def rotate(self, next_step: int) -> None:
        """Open a fresh log for the interval after snapshot ``next_step-1``
        and GC logs older than the newest durable snapshot."""
        with self._lock:
            if self._file is not None:
                self._file.close()
            self._file = open(self._path(next_step), "ab")
            for name in self._os.listdir(self._dir):
                if name.startswith("wal-") and name.endswith(".bin"):
                    try:
                        step = int(name[4:-4])
                    except ValueError:
                        continue
                    if step < next_step - 1:
                        try:
                            self._os.remove(
                                self._os.path.join(self._dir, name))
                        except OSError:
                            pass

    def append(self, kind: int, cid: int, seq: int, key: str,
               payload: bytes) -> None:
        from ..checkpoint.atomic import crc32_bytes

        kb = key.encode()
        body = (struct.pack("<BQQH", kind, cid, seq, len(kb)) + kb
                + bytes(payload))
        rec = struct.pack("<II", len(body), crc32_bytes(body)) + body
        with self._lock:
            if self._file is None:
                self._file = open(self._path(0), "ab")
            f = self._file
            f.write(rec)
            f.flush()
        if self._fsync:
            # fsync OUTSIDE the lock: it durably covers everything written
            # to the fd so far (including our record), and holding the one
            # WAL lock across per-push fsyncs would serialize pushes for
            # ALL keys behind disk latency — concurrent fsyncs on one fd
            # instead coalesce in the kernel (natural group commit)
            try:
                self._os.fsync(f.fileno())
            except (OSError, ValueError):
                # rotate/close raced us: close() already flushed the
                # record to the page cache, which survives a SIGKILL
                # (only a simultaneous power loss could drop it — the
                # same envelope as MXNET_PS_WAL_FSYNC=0)
                pass

    def replay(self, apply_fn) -> int:
        """Feed every intact record to ``apply_fn(kind, cid, seq, key,
        payload)`` in file/step order; a torn or corrupt record stops that
        file AND truncates it there — ``rotate`` may reopen the same file
        for appending, and a new acked record written *behind* a torn
        tail would be unreachable at the next replay (a silently lost
        acked push). Returns the number of records offered."""
        from ..checkpoint.atomic import crc32_bytes

        files = sorted(
            n for n in self._os.listdir(self._dir)
            if n.startswith("wal-") and n.endswith(".bin"))
        count = 0
        for name in files:
            path = self._os.path.join(self._dir, name)
            try:
                blob = open(path, "rb").read()
            except OSError:
                continue
            off = 0
            while off + 8 <= len(blob):
                ln, crc = struct.unpack_from("<II", blob, off)
                body = blob[off + 8:off + 8 + ln]
                if len(body) < ln or crc32_bytes(body) != crc:
                    break  # torn tail: that push was never acked
                kind, cid, seq, klen = struct.unpack_from("<BQQH", body, 0)
                key = body[19:19 + klen].decode()
                apply_fn(kind, cid, seq, key, body[19 + klen:])
                count += 1
                off += 8 + ln
            if off < len(blob):
                try:
                    with open(path, "r+b") as f:
                        f.truncate(off)
                except OSError:
                    pass
        return count

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------

class JoinInfo:
    """What a worker knows about its place in the fleet after a
    join / activation / epoch rendezvous."""

    __slots__ = ("active", "generation", "epoch", "part_index", "num_parts",
                 "active_count", "changed")

    def __init__(self, active, generation, epoch, part_index, num_parts,
                 active_count, changed=False):
        self.active = active
        self.generation = generation
        self.epoch = epoch
        self.part_index = part_index
        self.num_parts = num_parts
        self.active_count = active_count
        self.changed = changed

    def __repr__(self):
        return (f"JoinInfo(active={self.active}, gen={self.generation}, "
                f"epoch={self.epoch}, shard={self.part_index}/"
                f"{self.num_parts})")


class Heartbeater:
    """Background heartbeat sender on its OWN socket (the main client's
    single-RPC-at-a-time lock must never delay a heartbeat behind a
    blocking reduce). Connection failures back off with the shared jittered
    curve and never raise — a missing server looks like missed heartbeats,
    which is exactly what the liveness monitor is for."""

    def __init__(self, host: str, port: int, cid: int, rank: int,
                 interval: Optional[float] = None, part_provider=None):
        self._addr = (host, port)
        self._cid = cid
        self._rank = rank
        # training-fleet telemetry piggyback (obs/fleetstats.py): when the
        # provider returns a blob, it rides THIS beat after the 16-byte
        # identity header — no new connection, no new RPC; a None return
        # (nothing new / telemetry off) costs one call per beat
        self._part_provider = part_provider
        self.interval = (heartbeat_interval() if interval is None
                         else float(interval))
        self._sock = None
        self._stop = threading.Event()
        self.last_status = ST_OK
        self.generation = 0
        self.active_count = 0
        self._failures = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxtpu-elastic-heartbeat")
        self._thread.start()

    def _loop(self):
        import socket as _socket

        from ..base import capped_backoff, configure_socket_keepalive
        from .ps_server import OP_NAMES  # noqa: F401 — ensures names merged
        from .ps_server import _recv_msg, _send_msg

        payload = struct.pack("<QQ", self._cid, self._rank)
        pending = b""  # a drained part is destructive state: a failed
        # send keeps the blob for the next beat instead of losing that
        # rank's windows + spans to a transient connection blip
        while not self._stop.is_set():
            try:
                if self._sock is None:
                    self._sock = _socket.create_connection(
                        self._addr, timeout=max(2.0, self.interval * 4))
                    configure_socket_keepalive(self._sock)
                if not pending and self._part_provider is not None:
                    try:
                        pending = self._part_provider() or b""
                    except Exception:  # noqa: BLE001 — telemetry must
                        pending = b""  # never break the heartbeat
                _send_msg(self._sock, OP_HB, "", payload + pending)
                # clear on a successful SEND, not the ack: a lost reply
                # would re-ship the part and duplicate its spans in the
                # server cache (windows are index-keyed and idempotent,
                # spans are not); a send the kernel refused raises and
                # keeps the blob for the retry — the routine loss case
                pending = b""
                _, _, reply = _recv_msg(self._sock)
                self._failures = 0
                if len(reply) >= 13:
                    st, gen, count = struct.unpack_from("<BQI", reply, 0)
                    self.last_status = st
                    self.generation = gen
                    self.active_count = count
                obs.inc("elastic.heartbeats")
            except (ConnectionError, OSError):
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                self._failures += 1
                obs.inc("elastic.heartbeat_failures")
                self._stop.wait(capped_backoff(self._failures, self.interval,
                                               self.interval * 4))
                continue
            self._stop.wait(self.interval)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # the socket may be mid-backoff against a dead server; the
            # daemon thread dies with the process, but the leak is counted
            obs.inc("elastic.heartbeat_thread_leaked")


class ElasticWorkerSession:
    """Worker-side handle on the elastic plane (owned by the elastic
    :class:`~mxnet_tpu.kvstore.dist.DistKVStore`): join, heartbeat,
    generation-scoped allreduce, epoch rendezvous, checkpointed rejoin."""

    def __init__(self, host: str, port: int, rank: int = 0,
                 expected: Optional[int] = None,
                 hb_interval: Optional[float] = None,
                 reduce_timeout: Optional[float] = None,
                 part_provider="auto"):
        from .ps_client import PSClient

        self._cli = PSClient(host, port, timeout=30.0, retries=8,
                             retry_interval=0.2)
        # elastic servers are guaranteed to speak the ping opcode — turn on
        # idle ping-before-reuse unless explicitly configured off
        if self._cli._idle_ping_s is None:
            self._cli._idle_ping_s = 30.0
        self.cid = self._cli._client_id
        self.rank = int(rank)
        self._expected = expected
        self._reduce_timeout = (_reduce_timeout() if reduce_timeout is None
                                else float(reduce_timeout))
        self._hb_interval = hb_interval
        # "auto" = this process's real step accounting (obs/fleetstats.py)
        # rides the heartbeats; in-process multi-rank tests/benches pass
        # their per-rank accounting's wire_part, None disables
        if part_provider == "auto":
            from ..obs import fleetstats as _fleetstats

            part_provider = _fleetstats.wire_part
        self._part_provider = part_provider
        self._hb: Optional[Heartbeater] = None
        self._round = 0
        self._joined: Optional[JoinInfo] = None
        self.generation = 0

    # -- membership -----------------------------------------------------
    def ensure_joined(self, wait_for_expected: bool = True,
                      timeout: float = 30.0) -> JoinInfo:
        """Register with the fleet (idempotent). A cold-start fleet admits
        joiners as active; once training started, joins are quarantined
        until the next epoch boundary. With ``expected`` set (the launcher's
        ``DMLC_NUM_WORKER``), an active cold-start join waits briefly for
        the full expected fleet so the first shard cut is over all ranks."""
        if self._joined is not None:
            return self._joined
        info = self._join_rpc()
        if self._hb is None:
            self._hb = Heartbeater(self._cli._addr[0], self._cli._addr[1],
                                   self.cid, self.rank,
                                   interval=self._hb_interval,
                                   part_provider=self._part_provider)
        if (info.active and wait_for_expected and self._expected
                and info.active_count < self._expected):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                info = self._join_rpc()
                if info.active_count >= self._expected:
                    break
                time.sleep(0.05)
        self._joined = info
        self.generation = info.generation
        return info

    def _join_rpc(self) -> JoinInfo:
        payload = struct.pack("<QQ", self.cid, self.rank)
        _, _, reply = self._cli._rpc(OP_JOIN, "", payload)
        st, gen, epoch, part, nparts, count = struct.unpack_from(
            "<BQQIII", reply, 0)
        if st == ST_STALE:
            raise StaleMemberError(
                "this worker was declared dead by the fleet; restart the "
                "process to rejoin with a fresh identity")
        return JoinInfo(st == ST_OK, gen, epoch, part, nparts, count)

    def await_activation(self, timeout: Optional[float] = None) -> JoinInfo:
        """Block (server-side) until the next epoch boundary activates this
        quarantined worker; returns the post-activation assignment. Safe to
        retry — an already-active member gets the last release's reply."""
        timeout = _join_timeout() if timeout is None else float(timeout)
        obs.inc("elastic.quarantine_waits")
        with obs.trace.span("elastic.await_activation"):
            info = self._epoch_rpc(WAIT_ACTIVATION, timeout)
        self._round = 0
        self._joined = info
        obs.event("elastic.activated", epoch=info.epoch,
                  generation=info.generation, part=info.part_index,
                  nparts=info.num_parts)
        return info

    # -- collectives ----------------------------------------------------
    def allreduce(self, key: str, arr: np.ndarray,
                  timeout: Optional[float] = None):
        """Generation-scoped sum over the live fleet. Returns
        ``(summed, contributors)``. Retries are idempotent (the server
        dedups by cid and caches released rounds)."""
        from .ps_server import _pack_array, _unpack_array

        timeout = self._reduce_timeout if timeout is None else float(timeout)
        # the wait bound rides IN the request so the server always answers
        # (result or ST_ERROR) before the client's socket gives up — a
        # socket-timeout retry against a still-blocked round would just
        # stack handler threads
        payload = (struct.pack("<QQd", self.cid, self._round, timeout)
                   + _pack_array(np.ascontiguousarray(arr)))
        with obs.trace.span("elastic.allreduce", key=key,
                            round=self._round):
            _, _, reply = self._cli._rpc(OP_REDUCE, key, payload,
                                         timeout=timeout + 10.0)
        st, gen, contributors = struct.unpack_from("<BQI", reply, 0)
        if st == ST_STALE:
            raise StaleMemberError(
                f"reduce for key {key!r} rejected: this worker is not a "
                f"live member of generation {gen}")
        if st != ST_OK:
            raise ElasticError(
                f"elastic reduce timed out for key {key!r} round "
                f"{self._round} (generation {gen})")
        if gen != self.generation:
            obs.event("elastic.generation_observed", generation=gen,
                      contributors=contributors)
            self.generation = gen
        self._round += 1
        return _unpack_array(reply[13:]), contributors

    def allreduce_scoped(self, key: str, arr: np.ndarray, expected: int,
                         round_id: int, timeout: Optional[float] = None,
                         payload: Optional[bytes] = None):
        """Scoped sum: the round completes at ``expected`` distinct
        contributors instead of the full live membership — the transport
        under hierarchical reduction (``kvstore/dist.py``). ``round_id``
        is explicit: group members and leaders run different numbers of
        scoped rounds per step, so the session's flat counter cannot pace
        them. ``payload`` optionally carries pre-packed array bytes (the
        2-bit-compressed sparse wire from ``kvstore/compression.py``)."""
        from .ps_server import _pack_array, _unpack_array

        timeout = self._reduce_timeout if timeout is None else float(timeout)
        body = (_pack_array(np.ascontiguousarray(arr))
                if payload is None else payload)
        req = (struct.pack("<QQdI", self.cid, int(round_id), timeout,
                           int(expected)) + body)
        with obs.trace.span("elastic.allreduce_scoped", key=key,
                            round=int(round_id), expected=int(expected)):
            _, _, reply = self._cli._rpc(OP_REDUCE_SCOPED, key, req,
                                         timeout=timeout + 10.0)
        st, gen, contributors = struct.unpack_from("<BQI", reply, 0)
        if st == ST_STALE:
            raise StaleMemberError(
                f"scoped reduce for key {key!r} rejected: this worker is "
                f"not a live member of generation {gen}")
        if st != ST_OK:
            raise ElasticError(
                f"scoped reduce timed out for key {key!r} round "
                f"{round_id} (expected {expected} contributors)")
        return _unpack_array(reply[13:]), contributors

    def epoch_end(self, epoch: int, timeout: Optional[float] = None
                  ) -> JoinInfo:
        """Epoch-boundary rendezvous: blocks until every live member
        arrives (deaths shrink the requirement), activates quarantined
        rejoiners, and returns the possibly-recut shard assignment.
        Resets reduce-round numbering (the server cleared its tables)."""
        timeout = _join_timeout() if timeout is None else float(timeout)
        with obs.trace.span("elastic.epoch_end", epoch=epoch):
            info = self._epoch_rpc(int(epoch), timeout)
        prev = self._joined
        info.changed = (prev is None
                        or prev.part_index != info.part_index
                        or prev.num_parts != info.num_parts)
        self._round = 0
        self._joined = info
        return info

    def _epoch_rpc(self, epoch: int, timeout: float) -> JoinInfo:
        payload = struct.pack("<QQd", self.cid, epoch, timeout)
        _, _, reply = self._cli._rpc(OP_EPOCH, "", payload,
                                     timeout=timeout + 10.0)
        st, gen, nxt, part, nparts, count = struct.unpack_from(
            "<BQQIII", reply, 0)
        if st == ST_STALE:
            raise StaleMemberError(
                "epoch rendezvous rejected: this worker was declared dead")
        if st != ST_OK:
            raise ElasticError(
                f"epoch rendezvous timed out (epoch {epoch})")
        self.generation = gen
        return JoinInfo(True, gen, nxt, part, nparts, count)

    def barrier(self, timeout: float = 90.0):
        """Generation-scoped barrier (the server counts live members, not a
        static worker count, once anyone has joined)."""
        self._cli.barrier(timeout=timeout)

    # -- teardown -------------------------------------------------------
    def leave(self):
        try:
            self._cli._rpc(OP_LEAVE, "",
                           struct.pack("<Q", self.cid), retries=1)
        except MXNetError:
            pass  # the server may already be gone — liveness cleans up

    def close(self):
        if self._hb is not None:
            self._hb.stop()
            self._hb = None
        self.leave()
