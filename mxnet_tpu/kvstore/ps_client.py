"""Worker-side client for the async parameter server (see ps_server.py).

Failure handling (SURVEY.md §5.3, docs/ROBUSTNESS.md): every RPC has a
socket timeout and reconnect-retry with capped exponential backoff + jitter,
so a killed/restarted server looks like a slow RPC, not a worker crash.
Mutating RPCs (dense AND sparse pushes) carry a ``(client_id, seq)`` token
the server dedups on, and ``barrier()`` carries a barrier-epoch token —
so the retry path is exactly-once end to end, strictly stronger than the
reference ps-lite's at-least-once resend.

Chaos hooks: ``mxnet_tpu.chaos.rpc`` can deterministically drop / delay /
duplicate frames at the marked points below (one dict lookup when disabled).
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time

import numpy as np

from .. import obs
from ..obs import context as obs_context
from ..base import (MXNetError, capped_backoff, configure_socket_keepalive,
                    get_env)
from ..chaos import rpc as chaos_rpc
from .ps_server import (OP_BARRIER, OP_INIT, OP_PULL, OP_PULL_SPARSE,
                        OP_PUSH, OP_PUSH_SEQ, OP_PUSH_SPARSE,
                        OP_PUSH_SPARSE_SEQ, OP_SET_OPT, OP_SHUTDOWN,
                        OP_STATS, OP_TELEMETRY, _pack_array, _pack_sparse,
                        _recv_msg, _send_msg, _unpack_array)
from .elastic import OP_CLOCK, OP_CLOCK_PULL, OP_HB, OP_PULL_STALE, ST_OK


class PSClient:
    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 retries: int = 5, retry_interval: float = 0.5,
                 retry_max_interval: float = 5.0, idle_ping: float = None):
        self._addr = (host, port)
        self._timeout = timeout
        self._retries = max(1, int(retries))
        self._retry_interval = retry_interval
        self._retry_max_interval = retry_max_interval
        self._lock = threading.Lock()
        self._sock = None
        # half-open-connection detection (shared policy with the serve
        # client — base.configure_socket_keepalive): TCP keepalive on every
        # connection, plus a cheap ping-before-reuse once a connection has
        # sat idle past this threshold, so a dead server is detected at the
        # NEXT rpc instead of hanging until the OS keepalive gives up.
        # Ping needs a server that speaks OP_HB (the python server; elastic
        # sessions enable it) — MXNET_PS_IDLE_PING_S opts legacy/C++-server
        # fleets in explicitly; unset/None = keepalive only.
        self._idle_ping_s = (idle_ping if idle_ping is not None
                             else get_env("MXNET_PS_IDLE_PING_S", None,
                                          float))
        self._last_io = time.monotonic()
        # exactly-once pushes: (client_id, seq) dedups server-side, so a
        # retried PUSH whose reply was lost is NOT applied twice (stronger
        # than the reference ps-lite's at-least-once resend)
        self._client_id = int.from_bytes(os.urandom(8), "little")
        self._push_seq = 0  # guarded by _lock (allocated with the send)
        # barrier idempotency: the epoch token lets the server count a
        # retried arrival once, so a lost reply can't double-enter
        self._barrier_epoch = 0
        self._connect()

    def _connect(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)
        configure_socket_keepalive(self._sock)
        self._last_io = time.monotonic()

    def _ping_stale_connection(self):
        """Cheap OP_HB round-trip before reusing a long-idle connection; on
        any failure the socket is dropped so the caller's normal
        reconnect-retry path takes over (mirrors the serve client's
        lazy-connect discipline — never trust an idle socket)."""
        if (self._sock is None or not self._idle_ping_s
                or time.monotonic() - self._last_io < self._idle_ping_s):
            return
        try:
            self._sock.settimeout(min(self._timeout, 3.0))
            _send_msg(self._sock, OP_HB, "", b"")
            _recv_msg(self._sock)
            self._sock.settimeout(self._timeout)
            self._last_io = time.monotonic()
        except (ConnectionError, OSError):
            obs.inc("kvstore.rpc.stale_connections")
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _backoff(self, attempt: int) -> float:
        """Capped exponential backoff with full-range jitter (shared policy:
        ``base.capped_backoff`` — the serve client and replica pool use the
        same curve, so no plane reconnects in lockstep)."""
        return capped_backoff(attempt, self._retry_interval,
                              self._retry_max_interval)

    def _rpc(self, opcode, key="", payload=b"", timeout=None, retries=None):
        # the connection lock spans the send->recv roundtrip on purpose:
        # the PS wire is strictly serial per socket, and push() must pair
        # seq allocation with its send atomically; socket timeouts bound
        # every hold (hence the blocking-under-lock waivers here and at
        # the other _rpc_locked call sites)
        with self._lock:
            return self._rpc_locked(opcode, key, payload,  # lint: disable=blocking-call-under-lock
                                    timeout, retries)

    def _rpc_locked(self, opcode, key="", payload=b"", timeout=None,
                    retries=None):
        """Caller must hold self._lock (push() pairs seq allocation with the
        send inside one critical section)."""
        retries = self._retries if retries is None else retries
        last_err = None
        opname = chaos_rpc.OP_NAMES.get(opcode, str(opcode))
        for attempt in range(retries):
            try:
                self._ping_stale_connection()  # may drop a half-open sock
                if self._sock is None:
                    self._connect()
                if timeout is not None:
                    self._sock.settimeout(timeout)
                rec = obs.enabled()
                t0 = time.monotonic() if rec else 0.0
                with obs.trace.span("kvstore.rpc", op=opname, key=key,
                                    attempt=attempt):
                    # distributed tracing: inside a traced flow the key
                    # carries the kvstore.rpc span's context after \x1f
                    # (obs/context.py) — the python server strips it
                    # before any key lookup. Outside a trace (every plain
                    # training step) the key is untouched, so peers that
                    # predate context (the native C++ server) only ever
                    # see suffixed keys under an explicitly traced run;
                    # MXNET_OBS_WIRE=0 suppresses even that.
                    wire_key = obs_context.inject_key(
                        key, obs_context.current())
                    dup = chaos_rpc.on_send(opcode, key)
                    _send_msg(self._sock, opcode, wire_key, payload)
                    if dup == "dup":  # chaos: duplicated frame on the wire
                        _send_msg(self._sock, opcode, wire_key, payload)
                    reply = _recv_msg(self._sock)
                    if dup == "dup":
                        reply = _recv_msg(self._sock)  # drain the 2nd reply
                    chaos_rpc.on_reply(opcode, key)
                if rec:
                    obs.observe(f"kvstore.rpc.{opname}_seconds",
                                time.monotonic() - t0)
                    if opname in ("push", "push_seq", "push_sparse",
                                  "push_sparse_seq", "init"):
                        obs.inc("kvstore.bytes_pushed", len(payload))
                    elif opname in ("pull", "pull_sparse"):
                        obs.inc("kvstore.bytes_pulled", len(reply[2]))
                if timeout is not None:
                    self._sock.settimeout(self._timeout)
                self._last_io = time.monotonic()
                return reply
            except (ConnectionError, OSError) as e:  # incl. timeouts
                last_err = e
                if self._sock is not None:  # reconnect itself may fail
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                delay = self._backoff(attempt)
                if obs.enabled():
                    obs.inc("kvstore.rpc.retries")
                    obs.observe("kvstore.rpc.backoff_seconds", delay)
                    obs.trace.event("kvstore.rpc.retry", op=opname, key=key,
                                    attempt=attempt, error=str(e))
                time.sleep(delay)
        obs.inc("kvstore.rpc.failures")
        raise MXNetError(
            f"PS rpc op={opcode} key={key!r} failed after "
            f"{retries} attempts: {last_err}")

    def init(self, key: str, value: np.ndarray):
        self._rpc(OP_INIT, key, _pack_array(np.ascontiguousarray(value)))

    def push(self, key: str, grad: np.ndarray, compressor=None):
        if compressor is not None:
            payload = compressor.pack_wire(key, np.ascontiguousarray(grad))
        else:
            payload = _pack_array(np.ascontiguousarray(grad))
        # seq allocation and send are one critical section: out-of-order
        # same-key sends would make the server discard the lower seq as a
        # "duplicate" (silent gradient loss)
        with self._lock:
            self._push_seq += 1
            seq = self._push_seq
            _, _, reply = self._rpc_locked(  # lint: disable=blocking-call-under-lock
                OP_PUSH_SEQ, key,
                struct.pack("<QQ", self._client_id, seq) + payload)
        if bytes(reply[:1]) != b"\x00":
            raise MXNetError(
                f"push rejected for key {key!r} (uninitialized key or "
                "malformed frame)")

    def pull(self, key: str) -> np.ndarray:
        _, _, payload = self._rpc(OP_PULL, key)
        return _unpack_array(payload)

    def push_row_sparse(self, key: str, indices: np.ndarray,
                        rows: np.ndarray):
        """Push only the touched rows (reference sparse ZPush: wire moves
        len(indices) rows, not the full embedding matrix). Seq-tagged like
        the dense path, so a retried sparse push applies exactly once."""
        with self._lock:
            self._push_seq += 1
            seq = self._push_seq
            _, _, payload = self._rpc_locked(  # lint: disable=blocking-call-under-lock
                OP_PUSH_SPARSE_SEQ, key,
                struct.pack("<QQ", self._client_id, seq)
                + _pack_sparse(indices, rows))
        if bytes(payload[:1]) != b"\x00":
            raise MXNetError(
                f"sparse push rejected for key {key!r} (bad dtype, "
                "uninitialized key, or out-of-range row index)")

    def pull_row_sparse(self, key: str, indices: np.ndarray) -> np.ndarray:
        _, _, payload = self._rpc(
            OP_PULL_SPARSE, key,
            _pack_array(np.ascontiguousarray(indices, np.int32)))
        if len(payload) == 0:  # server signals failure with an empty reply
            raise MXNetError(
                f"sparse pull rejected for key {key!r} (uninitialized key "
                "or out-of-range row index)")
        return _unpack_array(payload)

    def push_clock(self, rank: int, step: int):
        """Commit "this rank FINISHED step ``step``" (``OP_CLOCK``,
        docs/ROBUSTNESS.md "Asynchronous training"). Max-merged and
        WAL-covered server-side, so retries are harmless and the table
        survives a server SIGKILL. Returns ``(floor, max_clock, widen)``
        — the fleet clock bounds ride the ack, so every step's commit
        doubles as the worker's staleness-view refresh."""
        _, _, reply = self._rpc(
            OP_CLOCK, "",
            struct.pack("<QQQ", self._client_id, int(rank), int(step)))
        st, floor, maxc, widen = struct.unpack_from("<BQQI", reply, 0)
        if st != ST_OK:
            raise MXNetError(f"clock push rejected for rank {rank}")
        return floor, maxc, widen

    def pull_clock(self):
        """The committed-clock table (``OP_CLOCK_PULL``): ``(floor,
        {rank: clock})`` — read-only; tests assert exactly-once clock
        recovery with it."""
        _, _, reply = self._rpc(OP_CLOCK_PULL, "")
        st, floor, n = struct.unpack_from("<BQI", reply, 0)
        if st != ST_OK:
            raise MXNetError("clock pull failed")
        table = {}
        for i in range(n):
            r, c = struct.unpack_from("<QQ", reply, 13 + 16 * i)
            table[int(r)] = int(c)
        return floor, table

    def pull_stale(self, key: str, rank: int, step: int, staleness: int,
                   timeout: float = 90.0):
        """Staleness-gated pull (``OP_PULL_STALE``): blocks server-side
        while this worker's committed clock ``step`` runs more than
        ``staleness`` (+ any policy widening) ahead of the fleet's
        committed-clock floor. The wait bound rides IN the request (the
        OP_REDUCE discipline) and the socket timeout sits above it, so a
        straggler-bound stall reports as a structured TimeoutError, not
        a dropped connection. Returns ``(weights, floor, max_clock)``."""
        payload = struct.pack("<QQQQd", self._client_id, int(rank),
                              int(step), int(staleness), float(timeout))
        _, _, reply = self._rpc(OP_PULL_STALE, key, payload,
                                timeout=timeout + 10.0)
        st, floor, maxc = struct.unpack_from("<BQQ", reply, 0)
        if st != ST_OK:
            raise TimeoutError(
                f"staleness-gated pull for key {key!r} timed out: this "
                f"rank's clock {step} is more than {staleness} steps "
                f"ahead of the fleet floor {floor} (slowest rank is the "
                "gate — see docs/ROBUSTNESS.md)")
        return _unpack_array(reply[17:]), floor, maxc

    def set_optimizer(self, optimizer):
        # text wire format shared with the C++ server (native/ps/ps_server.cc)
        name = type(optimizer).__name__.lower()
        kwargs = {"learning_rate": optimizer.lr, "wd": optimizer.wd,
                  "rescale_grad": optimizer.rescale_grad}
        mom = getattr(optimizer, "momentum", None)
        if mom:
            kwargs["momentum"] = mom
        for k in ("beta1", "beta2", "epsilon"):
            v = getattr(optimizer, k, None)
            if v is not None:
                kwargs[k] = v
        spec = name + " " + " ".join(f"{k}={v}" for k, v in kwargs.items())
        self._rpc(OP_SET_OPT, "", spec.encode("ascii"))

    def barrier(self, timeout: float = 90.0):
        """Idempotent rendezvous: the ``(client_id, barrier_epoch)`` token
        lets the server count a retried arrival (lost reply) once, so the
        full retry budget applies — the old ``retries=1`` special case that
        turned one dropped ack into a training abort is gone. May
        legitimately block for the server's straggler window."""
        with self._lock:
            # allocate-and-send in one critical section (like _push_seq):
            # concurrent callers must not share a token, and the epoch
            # advances even on failure — reusing the token at the NEXT
            # rendezvous could match this round's released entry and skip
            # the barrier entirely
            epoch = self._barrier_epoch
            self._barrier_epoch += 1
            payload = struct.pack("<QQ", self._client_id, epoch)
            _, _, reply = self._rpc_locked(OP_BARRIER, payload=payload,  # lint: disable=blocking-call-under-lock
                                           timeout=timeout)
        if bytes(reply[:1]) == b"\x01":
            # the server names exactly which ranks are missing (and their
            # last-heartbeat age) in a JSON detail after the status byte —
            # surface it instead of a generic straggler shrug
            detail = ""
            if len(reply) > 1:
                try:
                    import json

                    d = json.loads(bytes(reply[1:]).decode())
                    if d.get("stale_member"):
                        from .elastic import StaleMemberError

                        raise StaleMemberError(
                            "barrier rejected: this worker is not a live "
                            "fleet member (declared dead after missed "
                            "heartbeats); restart to rejoin")
                    missing = ", ".join(
                        f"rank {m['rank']} ({m['state']}, last heartbeat "
                        f"{m['last_heartbeat_age_s']}s ago)"
                        for m in d.get("missing", []))
                    detail = (f": {d.get('arrived')}/{d.get('expected')} "
                              f"arrived" + (f"; missing {missing}"
                                            if missing else ""))
                except (ValueError, KeyError, UnicodeDecodeError):
                    pass
            raise TimeoutError(
                "kvstore barrier timed out waiting for stragglers" + detail)

    def telemetry(self, drain: bool = True) -> dict:
        """Pull the training-fleet telemetry document (``OP_TELEMETRY``):
        ``{"parts": [...]}`` — the server's own part (RPC lanes + STATS
        with straggler verdicts and hot keys) plus every cached worker
        part. Draining is destructive and ``_rpc`` retries lost replies,
        so the request carries a fresh collection token: a retried frame
        re-serves the server's cached reply instead of draining (and
        silently losing) a second batch — the serve-plane idiom."""
        import json

        spec = {"drain": bool(drain), "token": os.urandom(8).hex()}
        _, _, reply = self._rpc(OP_TELEMETRY, "",
                                json.dumps(spec).encode("utf-8"))
        if bytes(reply[:1]) != b"\x00":
            raise MXNetError("PS telemetry failed: "
                             + bytes(reply[1:]).decode("utf-8", "replace"))
        return json.loads(bytes(reply[1:]).decode("utf-8"))

    def stats(self, include_metrics: bool = True) -> dict:
        """The server's structured STATS (``OP_STATS``): membership
        liveness, the training-fleet section, hot keys, and — by default
        — the metrics registry snapshot under ``"metrics"``."""
        import json

        payload = b"" if include_metrics \
            else json.dumps({"metrics": False}).encode("utf-8")
        _, _, reply = self._rpc(OP_STATS, "", payload)
        if bytes(reply[:1]) != b"\x00":
            raise MXNetError("PS stats failed: "
                             + bytes(reply[1:]).decode("utf-8", "replace"))
        return json.loads(bytes(reply[1:]).decode("utf-8"))

    def shutdown(self):
        self._rpc(OP_SHUTDOWN)

    def close(self):
        """End this client session (the server keeps running — unlike
        :meth:`shutdown`). Safe to call twice."""
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
