"""Worker-side client for the async parameter server (see ps_server.py)."""
from __future__ import annotations

import pickle
import socket
import threading

import numpy as np

from .ps_server import (OP_BARRIER, OP_INIT, OP_PULL, OP_PUSH, OP_SET_OPT,
                        OP_SHUTDOWN, _pack_array, _recv_msg, _send_msg,
                        _unpack_array)


class PSClient:
    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port), timeout=30)
        self._lock = threading.Lock()

    def _rpc(self, opcode, key="", payload=b""):
        with self._lock:
            _send_msg(self._sock, opcode, key, payload)
            return _recv_msg(self._sock)

    def init(self, key: str, value: np.ndarray):
        self._rpc(OP_INIT, key, _pack_array(np.ascontiguousarray(value)))

    def push(self, key: str, grad: np.ndarray):
        self._rpc(OP_PUSH, key, _pack_array(np.ascontiguousarray(grad)))

    def pull(self, key: str) -> np.ndarray:
        _, _, payload = self._rpc(OP_PULL, key)
        return _unpack_array(payload)

    def set_optimizer(self, optimizer):
        # text wire format shared with the C++ server (native/ps/ps_server.cc)
        name = type(optimizer).__name__.lower()
        kwargs = {"learning_rate": optimizer.lr, "wd": optimizer.wd,
                  "rescale_grad": optimizer.rescale_grad}
        mom = getattr(optimizer, "momentum", None)
        if mom:
            kwargs["momentum"] = mom
        for k in ("beta1", "beta2", "epsilon"):
            v = getattr(optimizer, k, None)
            if v is not None:
                kwargs[k] = v
        spec = name + " " + " ".join(f"{k}={v}" for k, v in kwargs.items())
        self._rpc(OP_SET_OPT, "", spec.encode("ascii"))

    def barrier(self):
        self._rpc(OP_BARRIER)

    def shutdown(self):
        self._rpc(OP_SHUTDOWN)
