"""Host-side asynchronous parameter server (dist_async transport).

Reference: ps-lite (``3rdparty/ps-lite``: ZMQ van, KVServer message loop,
server-side optimizer — TBV, SURVEY.md §3.4). TPU-native plan keeps this
**host-side over DCN** (north star): TPU workers push grads from host buffers,
the server applies the optimizer on arrival (no barrier — async), workers pull
fresh weights.

Transport: length-prefixed msgpack-free binary framing over TCP sockets
(stdlib only; the reference uses ZMQ which is not in this image). The server
runs one thread per connection + a lock per key, matching the reference's
per-key serialized updates. A C++ implementation of the same wire protocol
lives in native/ps (same framing), used when built.

Wire format (little-endian):
  u32 total_len | u8 opcode | u16 key_len | key bytes | payload
  opcodes: 0=INIT 1=PUSH 2=PULL 3=SET_OPT 4=BARRIER 5=SHUTDOWN
  payload for INIT/PUSH: u8 ndim | u32*ndim shape | u8 dtype_code | raw bytes
  reply for PULL: same array framing; others: u8 status
"""
from __future__ import annotations

import contextlib
import os
import pickle
import socket
import struct
import threading
import time
from typing import Dict, Optional

import numpy as np

from .. import obs
from ..obs import context as obs_context
from ..base import CODE_TO_DTYPE, DTYPE_TO_CODE

(OP_INIT, OP_PUSH, OP_PULL, OP_SET_OPT, OP_BARRIER, OP_SHUTDOWN,
 OP_PUSH_SPARSE, OP_PULL_SPARSE, OP_PUSH_SEQ, OP_PUSH_SPARSE_SEQ) = range(10)

# opcode → canonical name (telemetry labels; mxnet_tpu.chaos.rpc mirrors it)
OP_NAMES = {OP_INIT: "init", OP_PUSH: "push", OP_PULL: "pull",
            OP_SET_OPT: "set_opt", OP_BARRIER: "barrier",
            OP_SHUTDOWN: "shutdown", OP_PUSH_SPARSE: "push_sparse",
            OP_PULL_SPARSE: "pull_sparse", OP_PUSH_SEQ: "push_seq",
            OP_PUSH_SPARSE_SEQ: "push_sparse_seq"}


def _pack_array(arr: np.ndarray) -> bytes:
    code = DTYPE_TO_CODE[arr.dtype.name]
    head = struct.pack("<B", arr.ndim) + struct.pack(f"<{arr.ndim}I", *arr.shape) \
        + struct.pack("<B", code)
    return head + arr.tobytes()


def _unpack_array(buf: memoryview) -> np.ndarray:
    ndim = struct.unpack_from("<B", buf, 0)[0]
    shape = struct.unpack_from(f"<{ndim}I", buf, 1)
    code = struct.unpack_from("<B", buf, 1 + 4 * ndim)[0]
    if code == 16:  # 2-bit compressed gradient (see kvstore/compression.py)
        from .compression import dequantize_2bit

        size = int(np.prod(shape)) if ndim else 1
        off = 6 + 4 * ndim
        if len(buf) < off or len(buf) - off < (size + 3) // 4:
            raise ConnectionError("truncated 2-bit payload")  # drops the conn
        (threshold,) = struct.unpack_from("<f", buf, 2 + 4 * ndim)
        packed = np.frombuffer(buf, dtype=np.uint8, offset=off)
        return dequantize_2bit(packed, threshold, size).reshape(shape)
    dtype = np.dtype(CODE_TO_DTYPE[code])
    data = np.frombuffer(buf, dtype=dtype, offset=2 + 4 * ndim)
    return data.reshape(shape).copy()


def _array_nbytes(buf: memoryview) -> int:
    """Byte length of one packed array at the head of ``buf`` (so two arrays
    can ride one payload — the sparse wire format: indices then rows)."""
    ndim = struct.unpack_from("<B", buf, 0)[0]
    shape = struct.unpack_from(f"<{ndim}I", buf, 1)
    code = struct.unpack_from("<B", buf, 1 + 4 * ndim)[0]
    size = 1
    for s in shape:
        size *= s
    itemsize = np.dtype(CODE_TO_DTYPE[code]).itemsize
    return 2 + 4 * ndim + size * itemsize


def _pack_sparse(indices: np.ndarray, rows: np.ndarray) -> bytes:
    return (_pack_array(np.ascontiguousarray(indices, np.int32))
            + _pack_array(np.ascontiguousarray(rows)))


def _unpack_sparse(buf: memoryview):
    n = _array_nbytes(buf)
    return _unpack_array(buf[:n]), _unpack_array(buf[n:])


def _pack_arrays(arrays) -> bytes:
    """N arrays on one payload: u8 count, then each in the array framing
    above (the sparse wire generalized — mxnet_tpu.serve's multi-input
    requests and multi-output replies ride this)."""
    if len(arrays) > 255:
        raise ValueError(f"too many arrays for one frame ({len(arrays)})")
    return struct.pack("<B", len(arrays)) + b"".join(
        _pack_array(np.ascontiguousarray(a)) for a in arrays)


def _unpack_arrays(buf: memoryview):
    (count,) = struct.unpack_from("<B", buf, 0)
    out, off = [], 1
    for _ in range(count):
        n = _array_nbytes(buf[off:])
        out.append(_unpack_array(buf[off:off + n]))
        off += n
    return out, off


def _send_msg(sock: socket.socket, opcode: int, key: str = "", payload: bytes = b""):
    kb = key.encode()
    body = struct.pack("<BH", opcode, len(kb)) + kb + payload
    sock.sendall(struct.pack("<I", len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket):
    (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
    body = memoryview(_recv_exact(sock, ln))
    opcode, klen = struct.unpack_from("<BH", body, 0)
    key = bytes(body[3:3 + klen]).decode()
    payload = body[3 + klen:]
    return opcode, key, payload


class PSServer:
    """The server process: aggregates pushes and runs the optimizer per key.

    async mode (reference dist_async): every push immediately applies
    ``updater(key, grad, weight)`` under the key's lock — no worker barrier.
    """

    def __init__(self, host="0.0.0.0", port=9091, num_workers=1,
                 barrier_timeout=60.0):
        self._weights: Dict[str, np.ndarray] = {}
        self._locks: Dict[str, threading.Lock] = {}
        self._updater = None
        self._global_lock = threading.Lock()
        from collections import OrderedDict

        self._num_workers = num_workers
        # (client_id, key) -> last applied seq; LRU-bounded so client churn
        # (each process draws a fresh id) cannot grow the map forever.
        # Own lock: handlers for DIFFERENT keys share this dict, so the
        # per-key weight locks are not enough (mirrors the C++ seq_mu_).
        self._applied_seq: "OrderedDict" = OrderedDict()
        self._seq_lock = threading.Lock()
        self._barrier_timeout = barrier_timeout  # straggler window (seconds)
        self._barrier_count = 0
        self._barrier_gen = 0
        # idempotent barrier (docs/ROBUSTNESS.md): clients send a
        # (client_id, barrier_epoch) token; the arrival SET dedups a
        # retransmit within the round, and the released LRU acks a
        # retransmit that arrives after the round completed.
        self._barrier_arrived: Dict = {}
        self._barrier_released: "OrderedDict" = OrderedDict()
        self._barrier_cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._conns = []

    def serve_forever(self):
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.5)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._conns.append(conn)
            t = threading.Thread(target=self._handle, args=(conn,), daemon=True)
            t.start()
            self._threads = [th for th in self._threads if th.is_alive()]
            self._threads.append(t)

    def start(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # snapshot: _handle threads concurrently .remove() from _conns and
        # iterating the live list could skip a neighbor of a removed entry
        for c in list(self._conns):  # sever live sessions too — a stopped
            try:                     # server must look dead, not half-alive
                c.close()
            except OSError:
                pass

    # ------------------------------------------------------------------
    def _handle(self, conn: socket.socket):
        try:
            self._handle_loop(conn)
        finally:  # prune: reconnect-retrying clients make churn routine
            try:
                conn.close()
            except OSError:
                pass
            try:
                self._conns.remove(conn)
            except ValueError:
                pass

    def _handle_loop(self, conn: socket.socket):
        try:
            while True:
                opcode, key, payload = _recv_msg(conn)
                # strip wire trace context BEFORE any key lookup — a
                # context-bearing key must hit the same weight/lock/seq
                # tables as its plain form (old-format frames: no
                # separator, nothing stripped)
                key, wctx = obs_context.extract_key(key)
                rec = obs.enabled()
                t0 = time.monotonic() if rec else 0.0
                if rec:
                    obs.inc("kvstore.server.bytes_received", len(payload))
                try:
                    # server-side span joins the worker's trace, so a PS
                    # RPC shows both halves (client wait vs server apply)
                    # on the merged timeline
                    with obs_context.use(wctx), \
                            obs.trace.span(
                                "kvstore.server.rpc",
                                op=OP_NAMES.get(opcode, str(opcode)),
                                key=key):
                        alive = self._handle_one(conn, opcode, key, payload)
                finally:
                    if rec:
                        # per-RPC service time, server side (lock wait +
                        # optimizer apply + reply serialization)
                        obs.observe(
                            "kvstore.server.rpc."
                            f"{OP_NAMES.get(opcode, str(opcode))}_seconds",
                            time.monotonic() - t0)
                if not alive:
                    return
        except (ConnectionError, OSError):
            return

    def _handle_one(self, conn: socket.socket, opcode: int, key: str,
                    payload) -> bool:
        """Serve one framed request; False only after OP_SHUTDOWN."""
        if opcode == OP_INIT:
            arr = _unpack_array(payload)
            with self._global_lock:
                if key not in self._weights:
                    self._weights[key] = arr
                    self._locks[key] = threading.Lock()
            _send_msg(conn, OP_INIT, key, b"\x00")
        elif opcode == OP_PUSH:
            grad = _unpack_array(payload)
            with self._locks[key]:
                if self._updater is not None:
                    w = self._weights[key]
                    self._apply(key, grad, w)
                else:
                    self._weights[key] = self._weights[key] + grad
            _send_msg(conn, OP_PUSH, key, b"\x00")
        elif opcode == OP_PUSH_SEQ:
            # exactly-once push: payload prefixed with (client_id,
            # seq); a retried frame whose seq was already applied is
            # acked without re-applying — fixes the at-least-once
            # double-apply the plain PUSH retry path has
            if key not in self._weights or len(payload) < 16:
                _send_msg(conn, OP_PUSH_SEQ, key, b"\x01")
                return True
            cid, seq = struct.unpack_from("<QQ", payload, 0)
            grad = _unpack_array(payload[16:])
            with self._locks[key]:
                with self._seq_lock:
                    fresh = self._applied_seq.get((cid, key), -1) < seq
                if fresh:
                    if self._updater is not None:
                        self._apply(key, grad, self._weights[key])
                    else:
                        self._weights[key] = self._weights[key] + grad
                    # record only AFTER a successful apply, so a
                    # failed apply doesn't burn the seq
                    with self._seq_lock:
                        self._record_seq(cid, key, seq)
            _send_msg(conn, OP_PUSH_SEQ, key, b"\x00")
        elif opcode == OP_PULL:
            with self._locks.get(key, self._global_lock):
                arr = self._weights[key]
            _send_msg(conn, OP_PULL, key, _pack_array(arr))
        elif opcode == OP_PUSH_SPARSE:
            # reference kvstore_dist.h sparse PSKV: only touched rows
            # cross the wire; the server applies a row-sparse update.
            # Same validation contract as the C++ twin: bad key /
            # out-of-range or negative index → \x01, never corruption
            ok = self._apply_sparse(key, payload)
            _send_msg(conn, OP_PUSH_SPARSE, key,
                      b"\x00" if ok else b"\x01")
        elif opcode == OP_PUSH_SPARSE_SEQ:
            # sparse twin of OP_PUSH_SEQ: (client_id, seq) prefix
            # dedups a retried frame so the row update applies
            # exactly once even when the ack was lost
            if key not in self._weights or len(payload) < 16:
                _send_msg(conn, OP_PUSH_SPARSE_SEQ, key, b"\x01")
                return True
            cid, seq = struct.unpack_from("<QQ", payload, 0)
            ok = True
            with self._locks[key]:
                with self._seq_lock:
                    fresh = self._applied_seq.get((cid, key), -1) < seq
                if fresh:
                    ok = self._apply_sparse(key, payload[16:],
                                            locked=True)
                    if ok:  # a rejected frame must not burn the seq
                        with self._seq_lock:
                            self._record_seq(cid, key, seq)
            _send_msg(conn, OP_PUSH_SPARSE_SEQ, key,
                      b"\x00" if ok else b"\x01")
        elif opcode == OP_PULL_SPARSE:
            reply = b""  # empty = failure, matching the C++ twin
            if key in self._weights:
                idx = _unpack_array(payload).astype(np.int64)
                w = self._weights[key]
                if (idx.ndim == 1 and idx.size > 0
                        and 0 <= idx.min()
                        and idx.max() < w.shape[0]):
                    with self._locks.get(key, self._global_lock):
                        reply = _pack_array(
                            np.ascontiguousarray(w[idx]))
            _send_msg(conn, OP_PULL_SPARSE, key, reply)
        elif opcode == OP_SET_OPT:
            self._set_optimizer_bytes(bytes(payload))
            _send_msg(conn, OP_SET_OPT, key, b"\x00")
        elif opcode == OP_BARRIER:
            _send_msg(conn, OP_BARRIER, key,
                      b"\x00" if self._barrier(payload) else b"\x01")
        elif opcode == OP_SHUTDOWN:
            _send_msg(conn, OP_SHUTDOWN, key, b"\x00")
            self.stop()
            return False
        return True

    def _record_seq(self, cid, key, seq):
        """Caller holds ``self._seq_lock``. LRU-bounded (client churn)."""
        self._applied_seq[(cid, key)] = seq
        self._applied_seq.move_to_end((cid, key))
        while len(self._applied_seq) > 65536:
            self._applied_seq.popitem(last=False)

    def _apply_sparse(self, key, payload, locked=False) -> bool:
        """Validate + apply a row-sparse push. Returns False (never corrupts)
        on bad key / shape mismatch / out-of-range or negative index."""
        if key not in self._weights:
            return False
        idx, rows = _unpack_sparse(payload)
        idx = idx.astype(np.int64)
        w = self._weights[key]
        if not (idx.ndim == 1 and rows.shape[:1] == idx.shape
                and rows.shape[1:] == w.shape[1:] and idx.size > 0
                and 0 <= idx.min() and idx.max() < w.shape[0]):
            return False
        lock = self._locks[key] if not locked else contextlib.nullcontext()
        with lock:
            if self._updater is not None:
                grad = np.zeros_like(w)
                np.add.at(grad, idx, rows.astype(w.dtype))
                self._apply(key, grad, w)
            else:
                np.add.at(w, idx, rows.astype(w.dtype))
        return True

    def _barrier(self, payload) -> bool:
        """Generation-counted rendezvous; a straggler timeout rolls its
        arrival back instead of poisoning the next round.

        Idempotent when the client sends a (client_id, barrier_epoch) token
        (16-byte payload): a retransmit within the round is counted once
        (arrival keyed by token), and a retransmit that lands after the round
        released — the lost-reply case — is acked immediately from the
        released LRU instead of entering the next round. Tokenless legacy
        frames fall back to plain arrival counting.
        """
        token = (struct.unpack_from("<QQ", payload, 0)
                 if len(payload) >= 16 else None)
        ok = True
        with self._barrier_cv:
            counted = True
            if token is not None:
                if token in self._barrier_released:
                    return True  # round already completed; just re-ack
                if token in self._barrier_arrived:
                    # retransmit while the round is still gathering: wait for
                    # the release the original arrival is counted toward
                    gen = self._barrier_arrived[token]
                    counted = False
                else:
                    gen = self._barrier_gen
                    self._barrier_arrived[token] = gen
                    self._barrier_count += 1
            else:
                gen = self._barrier_gen
                self._barrier_count += 1
            if counted and self._barrier_count >= self._num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                for tok in self._barrier_arrived:
                    self._barrier_released[tok] = True
                self._barrier_arrived.clear()
                while len(self._barrier_released) > 65536:
                    self._barrier_released.popitem(last=False)
                self._barrier_cv.notify_all()
            else:
                deadline = time.monotonic() + self._barrier_timeout
                while self._barrier_gen == gen:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        # roll back only an arrival THIS handler counted; a
                        # timed-out retransmit must not erase the original's
                        if counted:
                            self._barrier_count = max(
                                0, self._barrier_count - 1)
                            if token is not None:
                                self._barrier_arrived.pop(token, None)
                        ok = False
                        break
                    self._barrier_cv.wait(timeout=remaining)
        return ok

    def _set_optimizer_bytes(self, blob: bytes):
        """SET_OPT payload is text: ``name key=val key=val …`` — a format the
        C++ server (native/ps/ps_server.cc) parses too. Legacy pickle blobs
        still accepted."""
        from ..optimizer import Updater, create as opt_create

        try:
            text = blob.decode("ascii")
            parts = text.split()
            name, kwargs = parts[0], {}
            for kv in parts[1:]:
                k, _, v = kv.partition("=")
                kwargs[k] = float(v)
        except (UnicodeDecodeError, ValueError, IndexError):
            spec = pickle.loads(blob)
            name, kwargs = spec["name"], spec["kwargs"]
        opt = opt_create(name, **kwargs)
        self._updater = Updater(opt)
        # Pre-warm the XLA executables for every known weight shape with a
        # THROWAWAY updater, in the background (warming inside this RPC
        # handler would stall SET_OPT past the client timeout): the first
        # real push must not eat multi-second compiles inside a client's
        # RPC window (the cause of the retry-double-apply flake this fixes
        # together with OP_PUSH_SEQ).

        with self._global_lock:  # OP_INIT mutates _weights concurrently
            snapshot = [(k, w.copy()) for k, w in self._weights.items()]

        def _warm(shapes=snapshot):
            try:
                from ..ndarray import array

                warm = Updater(opt_create(name, **kwargs))
                for k, w in shapes:
                    warm(k, array(np.zeros_like(w)), array(w))
            except Exception:
                pass  # warmup is best-effort

        threading.Thread(target=_warm, daemon=True).start()

    def _apply(self, key, grad, weight_np):
        """Run the fused optimizer update on host numpy via the framework ops
        (the server machine may have no TPU; jax-cpu executes)."""
        from ..ndarray import array

        w = array(weight_np)
        g = array(grad)
        self._updater(key, g, w)
        self._weights[key] = w.asnumpy()


def main():
    import argparse

    # The PS is host-side by design (reference ps-lite servers are CPU
    # processes): pin jax to cpu BEFORE any NDArray is created, or the
    # optimizer's first _apply would initialize the accelerator backend —
    # and hang forever when the axon tunnel is down (observed 2026-07-30:
    # every push RPC then times out). MXNET_PS_PLATFORM overrides.
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("MXNET_PS_PLATFORM", "cpu"))

    ap = argparse.ArgumentParser(description="mxnet_tpu async parameter server")
    ap.add_argument("--port", type=int, default=9091)
    ap.add_argument("--num-workers", type=int, default=1)
    args = ap.parse_args()
    srv = PSServer(port=args.port, num_workers=args.num_workers)
    print(f"PSServer listening on :{srv.port}", flush=True)
    srv.serve_forever()


if __name__ == "__main__":
    main()
