"""Host-side asynchronous parameter server (dist_async transport).

Reference: ps-lite (``3rdparty/ps-lite``: ZMQ van, KVServer message loop,
server-side optimizer — TBV, SURVEY.md §3.4). TPU-native plan keeps this
**host-side over DCN** (north star): TPU workers push grads from host buffers,
the server applies the optimizer on arrival (no barrier — async), workers pull
fresh weights.

Transport: length-prefixed msgpack-free binary framing over TCP sockets
(stdlib only; the reference uses ZMQ which is not in this image). The server
runs one thread per connection + a lock per key, matching the reference's
per-key serialized updates. A C++ implementation of the same wire protocol
lives in native/ps (same framing), used when built.

Wire format (little-endian):
  u32 total_len | u8 opcode | u16 key_len | key bytes | payload
  opcodes: 0=INIT 1=PUSH 2=PULL 3=SET_OPT 4=BARRIER 5=SHUTDOWN
  (6-9 sparse/seq variants; 16-20 elastic membership — see elastic.py;
  32-42 are the serving plane's range, serve/server.py)
  payload for INIT/PUSH: u8 ndim | u32*ndim shape | u8 dtype_code | raw bytes
  reply for PULL: same array framing; others: u8 status

Elastic training (docs/ROBUSTNESS.md "Elastic training"): with worker
heartbeats flowing, every barrier and epoch rendezvous is scoped to the
LIVE membership — a SIGKILL'd worker is declared dead after K missed
heartbeats and collective waits release over the survivors instead of
timing out. With ``snapshot_dir`` set the server also periodically
snapshots weights / optimizer state / the seq-dedup table through the
checkpoint/ atomic+CRC machinery and warm-restarts from the newest valid
snapshot, so a SIGKILL'd server comes back with exactly-once semantics
intact (clients retry with capped backoff; replayed pushes dedupe).
"""
from __future__ import annotations

import contextlib
import json
import os
import pickle
import socket
import struct
import threading
import time
from typing import Dict, Optional

import numpy as np

from .. import copytrack, obs, tsan
from ..obs import context as obs_context
from ..base import CODE_TO_DTYPE, DTYPE_TO_CODE, get_env
from ..wire import PS_WIRE
from . import elastic as elastic_mod
from .elastic import (ELASTIC_OP_NAMES, OP_CLOCK, OP_CLOCK_PULL, OP_EPOCH,
                      OP_HB, OP_JOIN, OP_LEAVE, OP_PULL_STALE, OP_REDUCE,
                      OP_REDUCE_SCOPED, ST_ERROR, ST_OK, ST_QUARANTINED,
                      ST_STALE)

# opcode constants come from the declarative registry (mxnet_tpu/wire.py):
# codes, names, and exactly-once metadata live in ONE table that the
# protocol linter cross-checks against this module's dispatch
(OP_INIT, OP_PUSH, OP_PULL, OP_SET_OPT, OP_BARRIER, OP_SHUTDOWN,
 OP_PUSH_SPARSE, OP_PULL_SPARSE, OP_PUSH_SEQ, OP_PUSH_SPARSE_SEQ,
 OP_TELEMETRY, OP_STATS) = \
    PS_WIRE.codes("init", "push", "pull", "set_opt", "barrier", "shutdown",
                  "push_sparse", "pull_sparse", "push_seq",
                  "push_sparse_seq", "telemetry", "stats")

# opcode → canonical name (telemetry labels; mxnet_tpu.chaos.rpc mirrors
# it) — includes the elastic range, which this server also dispatches
OP_NAMES = dict(PS_WIRE.names())

# one rule table fault-injects both planes (the serve/server.py idiom) —
# the full PS table, so the fleet-telemetry/stats ops are targetable too
from ..chaos import rpc as _chaos_rpc  # noqa: E402

_chaos_rpc.OP_NAMES.update(OP_NAMES)


def _pack_array(arr: np.ndarray) -> bytes:
    code = DTYPE_TO_CODE[arr.dtype.name]
    head = struct.pack("<B", arr.ndim) + struct.pack(f"<{arr.ndim}I", *arr.shape) \
        + struct.pack("<B", code)
    copytrack.TRACKER.serialized(arr.nbytes)
    copytrack.TRACKER.copied(arr.nbytes)
    # one copy of the array bytes into the frame is today's wire
    # contract; memoryview scatter-gather framing is ROADMAP item 4 —
    # copytrack counts this copy so the rewrite's gain is measurable
    return head + arr.tobytes()  # lint: disable=redundant-buffer-copy


def _unpack_array(buf: memoryview) -> np.ndarray:
    ndim = struct.unpack_from("<B", buf, 0)[0]
    shape = struct.unpack_from(f"<{ndim}I", buf, 1)
    code = struct.unpack_from("<B", buf, 1 + 4 * ndim)[0]
    if code == 16:  # 2-bit compressed gradient (see kvstore/compression.py)
        from .compression import dequantize_2bit

        size = int(np.prod(shape)) if ndim else 1
        off = 6 + 4 * ndim
        if len(buf) < off or len(buf) - off < (size + 3) // 4:
            raise ConnectionError("truncated 2-bit payload")  # drops the conn
        (threshold,) = struct.unpack_from("<f", buf, 2 + 4 * ndim)
        packed = np.frombuffer(buf, dtype=np.uint8, offset=off)
        return dequantize_2bit(packed, threshold, size).reshape(shape)
    dtype = np.dtype(CODE_TO_DTYPE[code])
    data = np.frombuffer(buf, dtype=dtype, offset=2 + 4 * ndim)
    copytrack.TRACKER.copied(data.nbytes)
    return data.reshape(shape).copy()


def _array_nbytes(buf: memoryview) -> int:
    """Byte length of one packed array at the head of ``buf`` (so two arrays
    can ride one payload — the sparse wire format: indices then rows)."""
    ndim = struct.unpack_from("<B", buf, 0)[0]
    shape = struct.unpack_from(f"<{ndim}I", buf, 1)
    code = struct.unpack_from("<B", buf, 1 + 4 * ndim)[0]
    size = 1
    for s in shape:
        size *= s
    itemsize = np.dtype(CODE_TO_DTYPE[code]).itemsize
    return 2 + 4 * ndim + size * itemsize


def _pack_sparse(indices: np.ndarray, rows: np.ndarray) -> bytes:
    return (_pack_array(np.ascontiguousarray(indices, np.int32))
            + _pack_array(np.ascontiguousarray(rows)))


def _unpack_sparse(buf: memoryview):
    n = _array_nbytes(buf)
    return _unpack_array(buf[:n]), _unpack_array(buf[n:])


def _pack_arrays(arrays) -> bytes:
    """N arrays on one payload: u8 count, then each in the array framing
    above (the sparse wire generalized — mxnet_tpu.serve's multi-input
    requests and multi-output replies ride this)."""
    if len(arrays) > 255:
        raise ValueError(f"too many arrays for one frame ({len(arrays)})")
    buf = struct.pack("<B", len(arrays)) + b"".join(
        _pack_array(np.ascontiguousarray(a)) for a in arrays)
    copytrack.TRACKER.copied(len(buf) - 1)  # the gather join re-copies
    return buf


def _unpack_arrays(buf: memoryview):
    (count,) = struct.unpack_from("<B", buf, 0)
    out, off = [], 1
    for _ in range(count):
        n = _array_nbytes(buf[off:])
        out.append(_unpack_array(buf[off:off + n]))
        off += n
    return out, off


def _send_msg(sock: socket.socket, opcode: int, key: str = "", payload=b""):
    """Frame and send one message. ``payload`` is ``bytes``/``memoryview``
    or a list of buffer parts — parts go straight to ``sendmsg`` without
    ever being concatenated (the scatter-gather send the data-plane lint
    demands: the old ``sendall(header + body)`` re-copied every message)."""
    kb = key.encode()
    parts = list(payload) if isinstance(payload, (list, tuple)) \
        else [payload]
    plen = sum(len(p) for p in parts)
    head = struct.pack("<IBH", 3 + len(kb) + plen, opcode, len(kb)) + kb
    _send_parts(sock, [head] + parts)


def _send_parts(sock, parts) -> None:
    """sendall() for a list of buffers, scatter-gather: no concatenation,
    resumes correctly after a partial ``sendmsg``."""
    views = [memoryview(p) for p in parts if len(p)]
    if not hasattr(sock, "sendmsg"):  # test/chaos socket doubles
        copytrack.TRACKER.copied(sum(len(v) for v in views))
        sock.sendall(b"".join(views))
        return
    while views:
        sent = sock.sendmsg(views)
        while sent:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        n -= len(c)
    if len(chunks) == 1:
        return chunks[0]  # single-chunk receive: join would be a no-op
    buf = b"".join(chunks)
    copytrack.TRACKER.copied(len(buf))  # multi-chunk reassembly copy
    return buf


def _recv_msg(sock: socket.socket):
    (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
    body = memoryview(_recv_exact(sock, ln))
    opcode, klen = struct.unpack_from("<BH", body, 0)
    key = bytes(body[3:3 + klen]).decode()
    payload = body[3 + klen:]
    return opcode, key, payload


class PSServer:
    """The server process: aggregates pushes and runs the optimizer per key.

    async mode (reference dist_async): every push immediately applies
    ``updater(key, grad, weight)`` under the key's lock — no worker barrier.
    """

    def __init__(self, host="0.0.0.0", port=9091, num_workers=1,
                 barrier_timeout=60.0, snapshot_dir=None,
                 snapshot_period=None, hb_interval=None, miss_k=None,
                 async_staleness=None):
        self._weights: Dict[str, np.ndarray] = {}
        self._locks: Dict[str, threading.Lock] = {}
        self._updater = None
        self._optimizer = None
        self._opt_spec: Optional[str] = None
        self._global_lock = tsan.lock("ps.global")
        from collections import OrderedDict

        self._num_workers = num_workers
        # elastic membership plane: created lazily at the first OP_JOIN so a
        # classic fleet (no heartbeats) pays nothing — not even the liveness
        # thread. The config is captured now for the lazy construction.
        self._elastic: Optional[elastic_mod.ElasticState] = None
        self._elastic_cfg = (hb_interval, miss_k)
        self._elastic_lock = tsan.lock("ps.elastic")
        # durable-state plane (docs/ROBUSTNESS.md "Elastic training"):
        # periodic snapshots through checkpoint/'s atomic+CRC manager, warm
        # restart from the newest valid one
        self._snapshot_dir = snapshot_dir or get_env(
            "MXNET_PS_SNAPSHOT_DIR", None)
        self._snapshot_period = float(
            snapshot_period if snapshot_period is not None
            else get_env("MXNET_PS_SNAPSHOT_PERIOD_S", 5.0, float))
        self._snap_mgr = None
        self._snap_step = 0
        self._snap_thread: Optional[threading.Thread] = None
        self._snap_lock = tsan.lock("ps.snapshot")
        self._wal: Optional[elastic_mod.PushWAL] = None
        # (client_id, key) -> last applied seq; LRU-bounded so client churn
        # (each process draws a fresh id) cannot grow the map forever.
        # Own lock: handlers for DIFFERENT keys share this dict, so the
        # per-key weight locks are not enough (mirrors the C++ seq_mu_).
        self._applied_seq: "OrderedDict" = OrderedDict()
        # key-indexed mirror of _applied_seq (same lock), so a durable
        # snapshot can copy ONE key's entries under that key's lock
        # instead of rescanning the 64k-entry LRU per key
        self._seq_by_key: Dict[str, Dict[int, int]] = {}
        self._seq_lock = tsan.lock("ps.seq")
        self._barrier_timeout = barrier_timeout  # straggler window (seconds)
        self._barrier_count = 0
        self._barrier_gen = 0
        # idempotent barrier (docs/ROBUSTNESS.md): clients send a
        # (client_id, barrier_epoch) token; the arrival SET dedups a
        # retransmit within the round, and the released LRU acks a
        # retransmit that arrives after the round completed.
        self._barrier_arrived: Dict = {}
        self._barrier_stamps: Dict = {}  # token -> arrival monotonic (the
        # per-rank barrier-wait attribution reads these at release)
        self._barrier_released: "OrderedDict" = OrderedDict()
        self._barrier_cv = tsan.condition("ps.barrier")
        # training-fleet telemetry plane (obs/fleetstats.py): cached
        # per-worker parts piggybacked on heartbeats + the straggler
        # detector over them; exactly-once OP_TELEMETRY drains via the
        # collection-token LRU (the serve-plane idiom)
        from ..obs import fleetstats as _fleetstats

        self.fleet = _fleetstats.FleetAggregator(
            member_ranks=self._live_ranks)
        self._hot_keys = _fleetstats.HotKeyTable()
        self._telemetry_tokens: "OrderedDict" = OrderedDict()
        self._telemetry_lock = tsan.lock("ps.telemetry")
        # bounded-staleness async plane (docs/ROBUSTNESS.md "Asynchronous
        # training"): per-rank committed clocks (rank -> last COMPLETED
        # step), the cid->rank table that attributes them, and the
        # per-rank staleness widening the straggler policy grants.
        # Initialized BEFORE _init_durability(): snapshot restore
        # (install_server_state) max-merges straight into these tables.
        # Lock order: _clock_cv may take el.cv (floor computation), never
        # the reverse — membership callbacks fire outside el.cv.
        self._clock: Dict[int, int] = {}
        self._clock_rank: Dict[int, int] = {}
        self._staleness_widen: Dict[int, int] = {}
        self._clock_cv = tsan.condition("ps.clock")
        if async_staleness is None:
            env = get_env("MXNET_ASYNC_STALENESS", None)
            async_staleness = int(env) if env is not None else None
        self._async_staleness = async_staleness
        self._async_widen_step = get_env("MXNET_ASYNC_WIDEN", 2, int)
        self._async_max_staleness = get_env(
            "MXNET_ASYNC_MAX_STALENESS", 16, int)
        if self._async_staleness is not None:
            # actuation (ROADMAP open item 2): straggler verdicts change
            # fleet behavior instead of only being reported. Registered
            # only in async mode so sync fleets keep PR 15 behavior.
            self.fleet.on_straggler(self._policy_on_straggler)
        self._started = time.monotonic()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._conns = []
        self._warm_thread: Optional[threading.Thread] = None
        if self._snapshot_dir:
            self._init_durability()

    def serve_forever(self):
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.5)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._conns.append(conn)
            t = threading.Thread(target=self._handle, args=(conn,), daemon=True)
            t.start()
            self._threads = [th for th in self._threads if th.is_alive()]
            self._threads.append(t)

    def start(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    # ------------------------------------------------------------------
    # elastic membership + durable state
    # ------------------------------------------------------------------
    def _elastic_state(self) -> elastic_mod.ElasticState:
        """The membership plane, created at the first OP_JOIN. The change
        callback pokes the barrier condvar so a declared death releases a
        waiting (now survivor-complete) barrier immediately."""
        with self._elastic_lock:
            if self._elastic is None:
                hb, miss = self._elastic_cfg
                self._elastic = elastic_mod.ElasticState(
                    hb_interval=hb, miss_k=miss,
                    on_change=[self._on_membership_change],
                    on_prune=[self.fleet.forget])
            return self._elastic

    def _on_membership_change(self):
        with self._barrier_cv:
            self._release_barrier_locked()
            self._barrier_cv.notify_all()
        # a declared death moves the committed-clock floor (dead ranks
        # stop holding it down) — staleness-gated pulls must re-check
        with self._clock_cv:
            self._clock_cv.notify_all()

    def _live_ranks(self):
        """Active members' ranks — the fleet aggregator's membership view
        (judging a window waits for every LIVE rank's report; dead/left
        ranks stop counting)."""
        el = self._elastic
        if el is None:
            return None
        with el.cv:
            return [m.rank for m in el.active_members()]

    def _required_workers(self) -> int:
        """Barrier quorum: the LIVE membership once anyone heartbeats, the
        static launch-time worker count otherwise (classic fleets)."""
        el = self._elastic
        if el is not None:
            with el.cv:
                if el.has_members():
                    return max(1, el.active_count())
        return self._num_workers

    # ------------------------------------------------------------------
    # bounded-staleness async clock plane (docs/ROBUSTNESS.md
    # "Asynchronous training")
    # ------------------------------------------------------------------
    def _clock_floor_locked(self) -> int:
        """Caller holds ``_clock_cv``. The committed-clock floor: min
        committed step over LIVE ranks — a live rank that has not
        committed yet floors at 0, so fast ranks cannot run away before
        the fleet's first commits land. Dead/left ranks drop out the
        moment liveness declares them (membership changes notify
        ``_clock_cv`` for exactly this). Without a membership plane every
        rank that ever committed counts."""
        live = self._live_ranks()
        if live:
            return min(self._clock.get(r, 0) for r in live)
        if not self._clock:
            return 0
        return min(self._clock.values())

    def _clock_bounds_locked(self):
        """Caller holds ``_clock_cv``: (floor, max clock, policy widen)."""
        floor = self._clock_floor_locked()
        maxc = max(self._clock.values()) if self._clock else 0
        widen = max(self._staleness_widen.values(), default=0)
        return floor, maxc, widen

    def _advance_clock(self, cid: int, rank: int, step: int) -> bool:
        """Commit "rank FINISHED step ``step``" — max-merge (a retried or
        reordered frame can never roll a clock back) and wake every
        staleness-gated pull. The advance rides the WAL (kind 4) before
        the caller acks, so a SIGKILLed server warm-restarts
        mid-async-storm with the clock table intact — the exactly-once
        contract extends to clocks."""
        with self._clock_cv:
            advanced = step > self._clock.get(rank, -1)
            if advanced:
                self._clock[rank] = step
                self._clock_cv.notify_all()
            self._clock_rank[cid] = rank
        if advanced and self._wal is not None:
            # append OUTSIDE _clock_cv: the fsync must not serialize the
            # gated-pull wakeups; still durable before the ack
            self._wal.append(4, cid, step, str(rank), b"")
        return advanced

    def _policy_on_straggler(self, verdict: dict):
        """``on_straggler`` actuation (async mode only — PR 15 built the
        sensor, this closes the loop): a compute-blamed straggler WIDENS
        the fleet's staleness bound (fast ranks run further ahead instead
        of stalling at the gate), a data_wait-blamed one triggers a shard
        recut (the pathological shard rotates off the rank at the next
        epoch boundary), and a recovery withdraws the widening. Runs on
        the heartbeat handler thread — exception containment lives in
        ``FleetAggregator._judge``, and this hook must return promptly
        (the SLOMonitor callback contract)."""
        kind = verdict.get("kind")
        rank = verdict.get("rank")
        if rank is None:
            return
        if kind == "recovered":
            with self._clock_cv:
                narrowed = self._staleness_widen.pop(rank, None)
                if narrowed is not None:
                    self._clock_cv.notify_all()
            if narrowed is not None:
                obs.event("train.async.staleness_narrowed", rank=rank,
                          was=narrowed)
            return
        if kind != "straggler":
            return
        blame = verdict.get("blame")
        if blame == "data_wait" and self._elastic is not None:
            self._elastic.request_recut()
            obs.event("train.async.shard_recut", rank=rank, blame=blame)
            return
        base = self._async_staleness or 0
        with self._clock_cv:
            cur = self._staleness_widen.get(rank, 0)
            new = min(cur + self._async_widen_step,
                      max(0, self._async_max_staleness - base))
            if new != cur:
                self._staleness_widen[rank] = new
                self._clock_cv.notify_all()
        if new != cur:
            obs.inc("train.async.staleness_widened")
            obs.event("train.async.staleness_widened", rank=rank,
                      widen=new, blame=blame or "compute")

    def _init_durability(self):
        from ..checkpoint.manager import CheckpointManager

        self._snap_mgr = CheckpointManager(self._snapshot_dir, prefix="ps",
                                           keep_last=3, async_write=False)
        state = self._snap_mgr.load_latest()
        if state is not None and state.meta.get("kind") == "ps_server":
            if state.meta.get("generation") is not None:
                self._elastic_state()  # restore generation monotonicity
            elastic_mod.install_server_state(self, state)
            self._snap_step = (self._snap_mgr.latest_step() or 0) + 1
        # replay acked-but-unsnapshotted pushes through the seq-dedup path
        # (anything the snapshot already covers skips itself), THEN open a
        # fresh log — zero lost, zero double-applied across the restart.
        # Two passes: key births (kind 2) first, then pushes in order —
        # the live handlers append birth and first-push records on
        # DIFFERENT locks, so a concurrent worker's acked push can land in
        # the log ahead of the key's birth record; a single ordered pass
        # would silently drop that acked push at `key not in weights`
        self._wal = elastic_mod.PushWAL(self._snapshot_dir)
        pushes = []

        def _births_first(kind, cid, seq, key, payload):
            if kind == 2:
                self._replay_push(kind, cid, seq, key, payload)
            else:
                pushes.append((kind, cid, seq, key, payload))

        replayed = self._wal.replay(_births_first)
        for rec in pushes:
            self._replay_push(*rec)
        if replayed:
            obs.event("elastic.ps_wal_replayed", records=replayed)
        self._wal.rotate(self._snap_step)
        if self._snapshot_period > 0:
            self._snap_thread = threading.Thread(
                target=self._snapshot_loop, daemon=True,
                name="mxtpu-ps-snapshot")
            self._snap_thread.start()

    def _replay_push(self, kind: int, cid: int, seq: int, key: str,
                     payload: bytes):
        """WAL replay: the OP_PUSH_SEQ / OP_PUSH_SPARSE_SEQ apply path
        minus the wire — dedup by (cid, seq), apply, record. Kind 2 is a
        key-birth record (OP_INIT): first-wins, like the live handler."""
        if kind == 2:
            with self._global_lock:
                if key not in self._weights:
                    self._weights[key] = _unpack_array(memoryview(payload))
                    self._locks[key] = tsan.lock("ps.key")
            return
        if kind == 3:  # optimizer spec (OP_SET_OPT), in order vs pushes
            spec = bytes(payload).decode("ascii", errors="replace")
            if spec != (self._opt_spec or "") or self._updater is None:
                # an unchanged spec from a WAL file overlapping the
                # snapshot must NOT rebuild the Updater — that would wipe
                # the snapshot-restored slots (momentum etc.)
                self._set_optimizer_bytes(bytes(payload), warm=False)
            return
        if kind == 4:  # committed-clock advance (OP_CLOCK): key is the
            # decimal rank, seq the step — max-merge, so replaying a
            # record older than the snapshot-restored clock is a no-op
            # and a clock can never roll back across a warm restart
            try:
                rank = int(key)
            except ValueError:
                return
            with self._clock_cv:
                if seq > self._clock.get(rank, -1):
                    self._clock[rank] = seq
                self._clock_rank[cid] = rank
            return
        if key not in self._weights:
            return
        buf = memoryview(payload)
        with self._locks[key]:
            with self._seq_lock:
                fresh = self._applied_seq.get((cid, key), -1) < seq
            if not fresh:
                return
            if kind == 0:
                grad = _unpack_array(buf)
                if self._updater is not None:
                    self._apply(key, grad, self._weights[key])
                else:
                    self._weights[key] = self._weights[key] + grad
            else:
                if not self._apply_sparse(key, buf, locked=True):
                    return
            with self._seq_lock:
                self._record_seq(cid, key, seq)

    def _snapshot_loop(self):
        while not self._stop.wait(self._snapshot_period):
            try:
                self.snapshot_now()
            except Exception:  # noqa: BLE001 — a failed snapshot must not
                obs.inc("elastic.ps_snapshot_errors")  # kill the server

    def snapshot_now(self):
        """Write one durable snapshot (atomic commit, CRC manifest). Safe
        to call concurrently with request handling: per-key consistency is
        taken under the same locks the push path applies under."""
        if self._snap_mgr is None:
            return
        with self._snap_lock:  # serialize: periodic vs explicit callers
            state = elastic_mod.capture_server_state(self)
            step, self._snap_step = self._snap_step, self._snap_step + 1
            with obs.trace.span("elastic.ps_snapshot", step=step):
                self._snap_mgr.save(state, step, block=True)
            if self._wal is not None:
                # pushes newer than this snapshot land in the fresh log;
                # older logs are covered by the snapshot and GC'd
                self._wal.rotate(step + 1)
            obs.inc("elastic.ps_snapshots")

    def stop(self):
        self._stop.set()
        if self._elastic is not None:
            self._elastic.close()
        if self._wal is not None:
            self._wal.close()
        try:
            self._sock.close()
        except OSError:
            pass
        # snapshot: _handle threads concurrently .remove() from _conns and
        # iterating the live list could skip a neighbor of a removed entry
        for c in list(self._conns):  # sever live sessions too — a stopped
            try:                     # server must look dead, not half-alive
                c.close()
            except OSError:
                pass
        # reap worker threads: handlers exit once their sockets are severed,
        # the snapshot loop and warm thread see _stop / finish their bounded
        # work. Leaks are counted, not waited out — stop() must be prompt.
        me = threading.current_thread()  # OP_SHUTDOWN stops from a handler
        reap = [t for t in self._threads if t is not me]
        if self._snap_thread is not None and self._snap_thread is not me:
            reap.append(self._snap_thread)
        if self._warm_thread is not None:
            reap.append(self._warm_thread)
        deadline = time.monotonic() + 1.0  # ONE budget for the whole reap
        leaked = 0
        for t in reap:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                leaked += 1
        if leaked:
            obs.inc("kvstore.server.threads_leaked", leaked)
            obs.event("kvstore.server.threads_leaked", count=leaked)

    # ------------------------------------------------------------------
    def _handle(self, conn: socket.socket):
        try:
            self._handle_loop(conn)
        finally:  # prune: reconnect-retrying clients make churn routine
            try:
                conn.close()
            except OSError:
                pass
            try:
                self._conns.remove(conn)
            except ValueError:
                pass

    def _handle_loop(self, conn: socket.socket):
        try:
            while True:
                opcode, key, payload = _recv_msg(conn)
                # strip wire trace context BEFORE any key lookup — a
                # context-bearing key must hit the same weight/lock/seq
                # tables as its plain form (old-format frames: no
                # separator, nothing stripped)
                key, wctx = obs_context.extract_key(key)
                rec = obs.enabled()
                t0 = time.monotonic() if rec else 0.0
                if rec:
                    obs.inc("kvstore.server.bytes_received", len(payload))
                try:
                    # server-side span joins the worker's trace, so a PS
                    # RPC shows both halves (client wait vs server apply)
                    # on the merged timeline
                    with obs_context.use(wctx), \
                            obs.trace.span(
                                "kvstore.server.rpc",
                                op=OP_NAMES.get(opcode, str(opcode)),
                                key=key):
                        alive = self._handle_one(conn, opcode, key, payload)
                finally:
                    if rec:
                        # per-RPC service time, server side (lock wait +
                        # optimizer apply + reply serialization)
                        obs.observe(
                            "kvstore.server.rpc."
                            f"{OP_NAMES.get(opcode, str(opcode))}_seconds",
                            time.monotonic() - t0)
                if not alive:
                    return
        except (ConnectionError, OSError):
            return

    def _handle_one(self, conn: socket.socket, opcode: int, key: str,
                    payload) -> bool:
        """Serve one framed request; False only after OP_SHUTDOWN."""
        if opcode == OP_INIT:
            arr = _unpack_array(payload)
            with self._global_lock:
                created = key not in self._weights
                if created:
                    self._weights[key] = arr
                    self._locks[key] = tsan.lock("ps.key")
            if created and self._wal is not None:
                # key birth rides the WAL (kind 2, one small fsynced
                # append) so a warm restart never sees a push for a key it
                # doesn't know — without paying a full-state snapshot per
                # key, let alone per re-init from every non-winning worker
                self._wal.append(2, 0, 0, key, bytes(payload))
            _send_msg(conn, OP_INIT, key, b"\x00")
        elif opcode == OP_PUSH:
            grad = _unpack_array(payload)
            with self._locks[key]:
                if self._updater is not None:
                    w = self._weights[key]
                    self._apply(key, grad, w)
                else:
                    self._weights[key] = self._weights[key] + grad
            _send_msg(conn, OP_PUSH, key, b"\x00")
        elif opcode == OP_PUSH_SEQ:
            # exactly-once push: payload prefixed with (client_id,
            # seq); a retried frame whose seq was already applied is
            # acked without re-applying — fixes the at-least-once
            # double-apply the plain PUSH retry path has
            if key not in self._weights or len(payload) < 16:
                _send_msg(conn, OP_PUSH_SEQ, key, b"\x01")
                return True
            cid, seq = struct.unpack_from("<QQ", payload, 0)
            grad = _unpack_array(payload[16:])
            from ..chaos.proc import kill_point

            rec = obs.enabled()
            t_apply = t_wal = 0.0
            with self._locks[key]:
                with self._seq_lock:
                    fresh = self._applied_seq.get((cid, key), -1) < seq
                if fresh:
                    t0 = time.monotonic() if rec else 0.0
                    if self._updater is not None:
                        self._apply(key, grad, self._weights[key])
                    else:
                        self._weights[key] = self._weights[key] + grad
                    if rec:
                        t_apply = time.monotonic() - t0
                    # record only AFTER a successful apply, so a
                    # failed apply doesn't burn the seq
                    with self._seq_lock:
                        self._record_seq(cid, key, seq)
                    if self._wal is not None:
                        # durable BEFORE the ack: an acked push may never
                        # be resent, so it must survive a SIGKILL here
                        t0 = time.monotonic() if rec else 0.0
                        self._wal.append(0, cid, seq, key,
                                         bytes(payload[16:]))
                        if rec:
                            t_wal = time.monotonic() - t0
            if rec and fresh:
                # reduce-plane attribution (docs/OBSERVABILITY.md
                # "Training-fleet telemetry"): optimizer-apply vs
                # WAL-append+fsync split per applied push, plus the
                # bounded top-N hot-key table train_report renders
                obs.observe("kvstore.server.push.apply_seconds", t_apply)
                if self._wal is not None:
                    obs.observe("kvstore.server.push.wal_seconds", t_wal)
                self._hot_keys.record(key, len(payload) - 16, t_apply)
            # chaos: die with the update applied+recorded but unacked —
            # the client MUST retry and the retry MUST dedupe, across a
            # warm restart when snapshots are on (docs/ROBUSTNESS.md)
            kill_point("ps:post_apply")
            kill_point("ps:pre_reply")
            _send_msg(conn, OP_PUSH_SEQ, key, b"\x00")
        elif opcode == OP_PULL:
            with self._locks.get(key, self._global_lock):
                arr = self._weights[key]
            rec = obs.enabled()
            t0 = time.monotonic() if rec else 0.0
            _send_msg(conn, OP_PULL, key, _pack_array(arr))
            if rec:
                # the serialize half of the per-RPC split (pushes reply
                # one status byte; pulls pay the array encode + send)
                obs.observe("kvstore.server.pull.serialize_seconds",
                            time.monotonic() - t0)
        elif opcode == OP_PUSH_SPARSE:
            # reference kvstore_dist.h sparse PSKV: only touched rows
            # cross the wire; the server applies a row-sparse update.
            # Same validation contract as the C++ twin: bad key /
            # out-of-range or negative index → \x01, never corruption
            ok = self._apply_sparse(key, payload)
            _send_msg(conn, OP_PUSH_SPARSE, key,
                      b"\x00" if ok else b"\x01")
        elif opcode == OP_PUSH_SPARSE_SEQ:
            # sparse twin of OP_PUSH_SEQ: (client_id, seq) prefix
            # dedups a retried frame so the row update applies
            # exactly once even when the ack was lost
            if key not in self._weights or len(payload) < 16:
                _send_msg(conn, OP_PUSH_SPARSE_SEQ, key, b"\x01")
                return True
            cid, seq = struct.unpack_from("<QQ", payload, 0)
            ok = True
            with self._locks[key]:
                with self._seq_lock:
                    fresh = self._applied_seq.get((cid, key), -1) < seq
                if fresh:
                    ok = self._apply_sparse(key, payload[16:],
                                            locked=True)
                    if ok:  # a rejected frame must not burn the seq
                        with self._seq_lock:
                            self._record_seq(cid, key, seq)
                        if self._wal is not None:
                            self._wal.append(1, cid, seq, key,
                                             bytes(payload[16:]))
            _send_msg(conn, OP_PUSH_SPARSE_SEQ, key,
                      b"\x00" if ok else b"\x01")
        elif opcode == OP_PULL_SPARSE:
            reply = b""  # empty = failure, matching the C++ twin
            if key in self._weights:
                idx = _unpack_array(payload).astype(np.int64)
                w = self._weights[key]
                if (idx.ndim == 1 and idx.size > 0
                        and 0 <= idx.min()
                        and idx.max() < w.shape[0]):
                    with self._locks.get(key, self._global_lock):
                        reply = _pack_array(
                            np.ascontiguousarray(w[idx]))
            _send_msg(conn, OP_PULL_SPARSE, key, reply)
        elif opcode == OP_SET_OPT:
            self._set_optimizer_bytes(bytes(payload))
            if self._wal is not None and self._opt_spec:
                # the spec must survive a restart — as one small WAL
                # record, not an inline full-state snapshot that could
                # stall this RPC past the client timeout on large models
                self._wal.append(3, 0, 0, "",
                                 self._opt_spec.encode("ascii"))
            _send_msg(conn, OP_SET_OPT, key, b"\x00")
        elif opcode == OP_BARRIER:
            ok, detail = self._barrier(payload)
            _send_msg(conn, OP_BARRIER, key,
                      b"\x00" if ok else b"\x01" + detail)
        elif opcode == OP_HB:
            # empty payload = connection-liveness ping (the client's
            # ping-before-reuse path) — replies without touching membership
            part_blob = cid = None
            if len(payload) >= 16:
                cid, _rank = struct.unpack_from("<QQ", payload, 0)
                if len(payload) > 16:
                    part_blob = payload[16:]
                st, gen, count = self._elastic_state().heartbeat(cid)
            elif self._elastic is not None:
                with self._elastic.cv:
                    st, gen, count = (ST_OK, self._elastic.generation,
                                      self._elastic.active_count())
            else:
                st, gen, count = ST_OK, 0, 0
            _send_msg(conn, OP_HB, key, struct.pack("<BQI", st, gen, count))
            if part_blob is not None:
                # training-fleet telemetry part piggybacked on the
                # heartbeat (obs/fleetstats.py): windowed step-phase
                # summaries + the rank's drained spans — ingested AFTER
                # last_hb was refreshed and the beat acked, so detector
                # judging and on_straggler policy hooks can never turn a
                # received heartbeat into a missed one (hooks must still
                # return promptly — the SLOMonitor callback contract)
                self.fleet.add_part(cid, part_blob)
        elif opcode == OP_JOIN:
            cid, rank = struct.unpack_from("<QQ", payload, 0)
            state, gen, epoch, part, nparts, count = \
                self._elastic_state().join(cid, rank)
            st = {"active": ST_OK, "quarantined": ST_QUARANTINED}.get(
                state, ST_STALE)
            _send_msg(conn, OP_JOIN, key,
                      struct.pack("<BQQIII", st, gen, epoch, part, nparts,
                                  count))
        elif opcode == OP_REDUCE:
            if self._elastic is None or len(payload) < 24:
                _send_msg(conn, OP_REDUCE, key,
                          struct.pack("<BQI", ST_ERROR, 0, 0))
                return True
            cid, round_id, wait = struct.unpack_from("<QQd", payload, 0)
            arr = _unpack_array(payload[24:])
            st, gen, n, result = self._elastic.reduce(
                cid, key, round_id, arr,
                timeout=max(1.0, min(float(wait), 3600.0)))
            head = struct.pack("<BQI", st, gen, n)
            _send_msg(conn, OP_REDUCE, key,
                      head + (_pack_array(result) if st == ST_OK else b""))
        elif opcode == OP_EPOCH:
            if self._elastic is None or len(payload) < 24:
                _send_msg(conn, OP_EPOCH, key,
                          struct.pack("<BQQIII", ST_ERROR, 0, 0, 0, 1, 0))
                return True
            cid, epoch, wait = struct.unpack_from("<QQd", payload, 0)
            st, gen, nxt, part, nparts, count = self._elastic.epoch_end(
                cid, epoch, timeout=max(1.0, min(float(wait), 3600.0)))
            _send_msg(conn, OP_EPOCH, key,
                      struct.pack("<BQQIII", st, gen, nxt, part, nparts,
                                  count))
        elif opcode == OP_LEAVE:
            if self._elastic is not None and len(payload) >= 8:
                (cid,) = struct.unpack_from("<Q", payload, 0)
                self._elastic.leave(cid)
            _send_msg(conn, OP_LEAVE, key, b"\x00")
        elif opcode == OP_CLOCK:
            # async committed-clock push: "rank r finished step t";
            # max-merge + kind-4 WAL record via _advance_clock. Reply
            # carries the fleet clock bounds so every step's commit
            # doubles as the worker's view refresh (floor for the gate,
            # max for lr compensation) — no extra RPC per step.
            if len(payload) < 24:
                _send_msg(conn, OP_CLOCK, key,
                          struct.pack("<BQQI", ST_ERROR, 0, 0, 0))
                return True
            cid, rank, step = struct.unpack_from("<QQQ", payload, 0)
            self._advance_clock(cid, int(rank), int(step))
            with self._clock_cv:
                floor, maxc, widen = self._clock_bounds_locked()
            _send_msg(conn, OP_CLOCK, key,
                      struct.pack("<BQQI", ST_OK, floor, maxc, widen))
        elif opcode == OP_CLOCK_PULL:
            # read-only committed-clock table dump — tests assert
            # exactly-once clock recovery with it; retries harmless
            with self._clock_cv:
                floor = self._clock_floor_locked()
                table = sorted(self._clock.items())
            _send_msg(conn, OP_CLOCK_PULL, key,
                      struct.pack("<BQI", ST_OK, floor, len(table))
                      + b"".join(struct.pack("<QQ", r, c)
                                 for r, c in table))
        elif opcode == OP_PULL_STALE:
            # staleness-gated pull (stale-synchronous-parallel): the
            # puller declares its own committed clock and blocks while it
            # would run more than s_eff steps ahead of the fleet's
            # committed-clock floor (s_eff = requested bound + policy
            # widening). The wait bound rides IN the request (the
            # OP_REDUCE discipline) so the server answers ST_ERROR before
            # the client socket timeout instead of dropping the
            # connection.
            if len(payload) < 40 or key not in self._weights:
                _send_msg(conn, OP_PULL_STALE, key,
                          struct.pack("<BQQ", ST_ERROR, 0, 0))
                return True
            cid, rank, step, stale, wait = struct.unpack_from(
                "<QQQQd", payload, 0)
            deadline = time.monotonic() + max(0.0, min(float(wait), 3600.0))
            st, blocked = ST_OK, False
            with self._clock_cv:
                while True:
                    floor, maxc, widen = self._clock_bounds_locked()
                    if step <= floor + stale + widen:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        st = ST_ERROR
                        obs.inc("kvstore.async.gate_timeouts")
                        break
                    blocked = True
                    self._clock_cv.wait(timeout=remaining)
            if blocked:
                obs.inc("kvstore.async.gate_blocks")
            if st != ST_OK:
                _send_msg(conn, OP_PULL_STALE, key,
                          struct.pack("<BQQ", st, floor, maxc))
                return True
            with self._locks.get(key, self._global_lock):
                arr = self._weights[key]
            _send_msg(conn, OP_PULL_STALE, key,
                      [struct.pack("<BQQ", ST_OK, floor, maxc),
                       _pack_array(arr)])
        elif opcode == OP_REDUCE_SCOPED:
            # scoped reduce: completes at an explicit contributor count
            # instead of the full live membership — the group-local and
            # cross-group stages of hierarchical reduction ride this
            if self._elastic is None or len(payload) < 28:
                _send_msg(conn, OP_REDUCE_SCOPED, key,
                          struct.pack("<BQI", ST_ERROR, 0, 0))
                return True
            cid, round_id, wait, expected = struct.unpack_from(
                "<QQdI", payload, 0)
            arr = _unpack_array(payload[28:])
            st, gen, n, result = self._elastic.reduce(
                cid, key, round_id, arr,
                timeout=max(1.0, min(float(wait), 3600.0)),
                expected=int(expected))
            head = struct.pack("<BQI", st, gen, n)
            _send_msg(conn, OP_REDUCE_SCOPED, key,
                      head + (_pack_array(result) if st == ST_OK else b""))
        elif opcode == OP_TELEMETRY:
            # training-fleet telemetry pull: this server's own part (its
            # kvstore.server.rpc lanes + STATS) plus every cached worker
            # part. Draining is destructive and the client retries lost
            # replies, so a collection token re-serves the cached reply
            # instead of draining (and losing) a second batch — the
            # serve-plane OP_TELEMETRY idiom.
            try:
                spec = json.loads(bytes(payload).decode("utf-8")) \
                    if len(payload) else {}
                token = spec.get("token")
                drain = bool(spec.get("drain", True))
                if token is None:
                    blob = json.dumps(self.telemetry(drain=drain),
                                      default=float).encode("utf-8")
                else:
                    # lookup AND drain under ONE lock hold: a retried
                    # token racing the original's in-flight drain would
                    # otherwise miss the cache and drain a second batch —
                    # the first batch then sits under the token, never
                    # re-requested (exactly the loss the token prevents).
                    # The drain is CPU-only (ring + dicts), so holding
                    # the lock serializes rare operator pulls, not RPCs.
                    with self._telemetry_lock:
                        blob = self._telemetry_tokens.get(token)
                        if blob is None:
                            blob = json.dumps(
                                self.telemetry(drain=drain),
                                default=float).encode("utf-8")
                            self._telemetry_tokens[token] = blob
                            while len(self._telemetry_tokens) > 16:
                                self._telemetry_tokens.popitem(last=False)
                _send_msg(conn, OP_TELEMETRY, key, b"\x00" + blob)
            except Exception as e:  # noqa: BLE001 — wire-reported
                obs.inc("kvstore.telemetry_errors")
                _send_msg(conn, OP_TELEMETRY, key,
                          b"\x01" + f"{type(e).__name__}: {e}".encode(
                              "utf-8", "replace"))
        elif opcode == OP_STATS:
            # read-only stats snapshot (membership liveness, straggler
            # verdicts, hot keys, metrics under "metrics" — the serve
            # plane's STATS schema); {"metrics": false} skips the
            # registry snapshot for cheap polls
            try:
                include = True
                if len(payload):
                    try:
                        spec = json.loads(bytes(payload).decode("utf-8"))
                        include = bool(spec.get("metrics", True))
                    except ValueError:
                        pass
                blob = json.dumps(self.stats(include_metrics=include),
                                  default=str).encode("utf-8")
                _send_msg(conn, OP_STATS, key, b"\x00" + blob)
            except Exception as e:  # noqa: BLE001 — wire-reported
                obs.inc("kvstore.stats_errors")
                _send_msg(conn, OP_STATS, key,
                          b"\x01" + f"{type(e).__name__}: {e}".encode(
                              "utf-8", "replace"))
        elif opcode == OP_SHUTDOWN:
            if self._snap_mgr is not None:
                try:
                    self.snapshot_now()  # parting durable state
                except Exception:  # noqa: BLE001
                    pass
            _send_msg(conn, OP_SHUTDOWN, key, b"\x00")
            self.stop()
            return False
        return True

    # ------------------------------------------------------------------
    # stats + telemetry surfaces (the serve-plane schema on the PS plane)
    # ------------------------------------------------------------------
    def stats(self, include_metrics: bool = True) -> dict:
        """Structured server state: key count, membership liveness, the
        training-fleet section (per-rank windows + straggler verdicts),
        the bounded hot-key table, and — ``include_metrics`` — the full
        registry snapshot under ``"metrics"`` (ONE schema for every
        numeric runtime signal, the serve-plane STATS discipline)."""
        out = {"pid": os.getpid(),
               "uptime_seconds": round(time.monotonic() - self._started, 3),
               "keys": len(self._weights),
               "num_workers": self._num_workers}
        el = self._elastic
        if el is not None:
            with el.cv:
                out["generation"] = el.generation
                out["epoch"] = el.epoch
                out["active_workers"] = el.active_count()
            out["membership"] = [
                {"rank": rank, "client_id": str(cid), "state": state,
                 "last_hb_age_s": age}
                for rank, cid, state, age in el.liveness_table()]
        out["fleet"] = self.fleet.stats()
        out["hot_keys"] = self._hot_keys.snapshot()
        with self._clock_cv:
            if self._clock or self._async_staleness is not None:
                floor, maxc, widen = self._clock_bounds_locked()
                out["async"] = {
                    "staleness": self._async_staleness,
                    "clock_floor": floor, "clock_max": maxc,
                    "widen": widen,
                    "clocks": {str(r): c
                               for r, c in sorted(self._clock.items())},
                    "staleness_widen": {
                        str(r): w for r, w
                        in sorted(self._staleness_widen.items())}}
        if include_metrics:
            out["metrics"] = obs.metrics.snapshot()
        return out

    def telemetry(self, drain: bool = True) -> dict:
        """``{"parts": [...]}`` — the OP_TELEMETRY document: this
        process's part first (role ``ps_server``, STATS attached so one
        pull carries the straggler verdicts and hot keys), then every
        cached worker part (role ``rank<r>``) with its windows, spans,
        and clock anchor — the rank lanes of the merged timeline."""
        st = self.stats(include_metrics=False)
        part = obs.telemetry_part(drain=drain, role="ps_server")
        part["stats"] = st
        return {"parts": [part] + self.fleet.parts(drain=drain)}

    def _record_seq(self, cid, key, seq):
        """Caller holds ``self._seq_lock``. LRU-bounded (client churn)."""
        self._applied_seq[(cid, key)] = seq
        self._applied_seq.move_to_end((cid, key))
        self._seq_by_key.setdefault(key, {})[cid] = seq
        while len(self._applied_seq) > 65536:
            (ocid, okey), _oseq = self._applied_seq.popitem(last=False)
            per_key = self._seq_by_key.get(okey)
            if per_key is not None:
                per_key.pop(ocid, None)
                if not per_key:
                    del self._seq_by_key[okey]

    def _apply_sparse(self, key, payload, locked=False) -> bool:
        """Validate + apply a row-sparse push. Returns False (never corrupts)
        on bad key / shape mismatch / out-of-range or negative index."""
        if key not in self._weights:
            return False
        idx, rows = _unpack_sparse(payload)
        idx = idx.astype(np.int64)
        w = self._weights[key]
        if not (idx.ndim == 1 and rows.shape[:1] == idx.shape
                and rows.shape[1:] == w.shape[1:] and idx.size > 0
                and 0 <= idx.min() and idx.max() < w.shape[0]):
            return False
        lock = self._locks[key] if not locked else contextlib.nullcontext()
        with lock:
            if self._updater is not None:
                grad = np.zeros_like(w)
                np.add.at(grad, idx, rows.astype(w.dtype))
                self._apply(key, grad, w)
            else:
                np.add.at(w, idx, rows.astype(w.dtype))
        return True

    def _release_barrier_locked(self) -> bool:
        """Caller holds ``_barrier_cv``. Releases the round when the quorum
        — the LIVE membership under elasticity, the static worker count
        otherwise — has arrived. Called on arrival AND on membership
        change, so a declared death releases a survivor-complete round.

        Membership-scoped release compares the live cid SET against the
        arrived token cids (the reduce/epoch discipline), not a raw count:
        a member that arrived and then died must not stand in for a live
        member that never reached the barrier."""
        el = self._elastic
        required_cids = None
        if el is not None:
            with el.cv:
                if el.has_members():
                    required_cids = {m.cid for m in el.active_members()}
        if required_cids is None:
            if self._barrier_count < self._num_workers:
                return False
        else:
            arrived = {tok[0] for tok, g in self._barrier_arrived.items()
                       if g == self._barrier_gen}
            # (tokenless legacy arrivals carry no identity and only count
            # in the static-quorum mode above)
            if not arrived or not required_cids.issubset(arrived):
                return False
        if obs.enabled() and el is not None and self._barrier_stamps:
            # barrier wait-by-rank (reduce-plane attribution): how long
            # each arrived rank stood at this rendezvous — the rank with
            # ~zero wait is the one everyone else waited on
            now = time.monotonic()
            with el.cv:
                rank_of = {m.cid: m.rank for m in el.members.values()}
            for tok, t0 in self._barrier_stamps.items():
                r = rank_of.get(tok[0])
                if r is not None:
                    obs.observe(f"kvstore.barrier_wait.rank{r}_seconds",
                                now - t0)
        self._barrier_count = 0
        self._barrier_gen += 1
        for tok in self._barrier_arrived:
            self._barrier_released[tok] = True
        self._barrier_arrived.clear()
        self._barrier_stamps.clear()
        while len(self._barrier_released) > 65536:
            self._barrier_released.popitem(last=False)
        self._barrier_cv.notify_all()
        return True

    def _barrier_timeout_detail(self) -> bytes:
        """Structured straggler report: exactly which ranks are missing and
        how stale their heartbeats are (unknowable without the membership
        plane — then only the arrived/expected counts are reported). Rides
        after the \\x01 status byte; also emitted as a
        ``kvstore.barrier_timeout`` obs event."""
        detail = {"expected": self._required_workers(),
                  "arrived": self._barrier_count}
        if self._elastic is not None:
            arrived_cids = {tok[0] for tok, g in self._barrier_arrived.items()
                            if g == self._barrier_gen}
            missing = [
                {"rank": rank, "client_id": cid, "state": state,
                 "last_heartbeat_age_s": age}
                for rank, cid, state, age in self._elastic.liveness_table()
                if state == "active" and cid not in arrived_cids]
            detail["missing"] = sorted(missing, key=lambda m: m["rank"])
        obs.event("kvstore.barrier_timeout", **{
            k: v for k, v in detail.items() if k != "missing"},
            missing_ranks=[m["rank"] for m in detail.get("missing", [])])
        obs.inc("kvstore.barrier_timeouts")
        try:
            return json.dumps(detail).encode()
        except (TypeError, ValueError):
            return b"{}"

    def _barrier(self, payload):
        """Membership-scoped rendezvous; a straggler timeout rolls its
        arrival back instead of poisoning the next round, and reports a
        structured straggler detail (returns ``(ok, detail_bytes)``).

        Idempotent when the client sends a (client_id, barrier_epoch) token
        (16-byte payload): a retransmit while the round gathers is counted
        once (arrival keyed by token), and a retransmit that lands after the
        round released — the lost-reply case — is acked immediately from the
        released LRU instead of entering the next round. Tokenless legacy
        frames fall back to plain arrival counting.

        With the elastic membership plane active the quorum is the LIVE
        member count: a worker SIGKILL'd mid-epoch is declared dead after K
        missed heartbeats and the round releases over the survivors —
        the barrier is scoped to the membership generation, not a static
        worker count.
        """
        token = (struct.unpack_from("<QQ", payload, 0)
                 if len(payload) >= 16 else None)
        # membership-scoped quorum needs membership-checked ARRIVALS too: a
        # zombie (declared dead, still running) counting toward the live
        # quorum would release a round a live member never reached —
        # reject it structurally, like OP_REDUCE's ST_STALE. Tokenless
        # legacy frames carry no identity and keep counting (no members →
        # static quorum → unchanged behavior).
        if token is not None and self._elastic is not None:
            with self._elastic.cv:
                if self._elastic.has_members():
                    m = self._elastic.members.get(token[0])
                    if m is None or m.state != "active":
                        obs.inc("elastic.stale_rejected")
                        return False, json.dumps(
                            {"stale_member": True,
                             "client_id": token[0]}).encode()
        ok, detail = True, b""
        with self._barrier_cv:
            counted = True
            if token is not None:
                if token in self._barrier_released:
                    return True, b""  # round completed; just re-ack
                if token in self._barrier_arrived:
                    # retransmit while the round is still gathering: wait for
                    # the release the original arrival is counted toward
                    gen = self._barrier_arrived[token]
                    counted = False
                else:
                    gen = self._barrier_gen
                    self._barrier_arrived[token] = gen
                    self._barrier_stamps[token] = time.monotonic()
                    self._barrier_count += 1
            else:
                gen = self._barrier_gen
                self._barrier_count += 1
            if not (counted and self._release_barrier_locked()):
                deadline = time.monotonic() + self._barrier_timeout
                while self._barrier_gen == gen:
                    # re-check on every wake: a membership change may have
                    # shrunk the quorum to the already-arrived set
                    if self._release_barrier_locked():
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        detail = self._barrier_timeout_detail()
                        # roll back only an arrival THIS handler counted; a
                        # timed-out retransmit must not erase the original's
                        if counted:
                            self._barrier_count = max(
                                0, self._barrier_count - 1)
                            if token is not None:
                                self._barrier_arrived.pop(token, None)
                                self._barrier_stamps.pop(token, None)
                        ok = False
                        break
                    self._barrier_cv.wait(timeout=remaining)
        return ok, detail

    def _set_optimizer_bytes(self, blob: bytes, warm: bool = True):
        """SET_OPT payload is text: ``name key=val key=val …`` — a format the
        C++ server (native/ps/ps_server.cc) parses too. Legacy pickle blobs
        still accepted. ``warm=False`` skips the background XLA pre-warm
        (the warm-restart path re-installs the optimizer before serving)."""
        from ..optimizer import Updater, create as opt_create

        try:
            text = blob.decode("ascii")
            parts = text.split()
            name, kwargs = parts[0], {}
            for kv in parts[1:]:
                k, _, v = kv.partition("=")
                kwargs[k] = float(v)
            self._opt_spec = text
        except (UnicodeDecodeError, ValueError, IndexError):
            # legacy SET_OPT blobs: a tiny {name, kwargs} dict set once at
            # init — never an array payload, never per-request
            spec = pickle.loads(blob)  # lint: disable=pickle-on-wire
            name, kwargs = spec["name"], spec["kwargs"]
            # normalize to the text form so a durable snapshot can always
            # re-install it (capture_server_state persists _opt_spec)
            self._opt_spec = name + " " + " ".join(
                f"{k}={v}" for k, v in kwargs.items())
        opt = opt_create(name, **kwargs)
        self._optimizer = opt
        self._updater = Updater(opt)
        if not warm:
            return
        # Pre-warm the XLA executables for every known weight shape with a
        # THROWAWAY updater, in the background (warming inside this RPC
        # handler would stall SET_OPT past the client timeout): the first
        # real push must not eat multi-second compiles inside a client's
        # RPC window (the cause of the retry-double-apply flake this fixes
        # together with OP_PUSH_SEQ).

        with self._global_lock:  # OP_INIT mutates _weights concurrently
            snapshot = [(k, w.copy()) for k, w in self._weights.items()]

        def _warm(shapes=snapshot):
            try:
                from ..ndarray import array

                warm = Updater(opt_create(name, **kwargs))
                for k, w in shapes:
                    warm(k, array(np.zeros_like(w)), array(w))
            except Exception:
                pass  # warmup is best-effort

        # tracked (not fire-and-forget): stop() joins it with a bounded
        # timeout so a mid-compile warmup can't outlive the server silently
        self._warm_thread = threading.Thread(target=_warm, daemon=True,
                                             name="mxtpu-ps-warm")
        self._warm_thread.start()

    def _apply(self, key, grad, weight_np):
        """Run the fused optimizer update on host numpy via the framework ops
        (the server machine may have no TPU; jax-cpu executes)."""
        from ..ndarray import array

        w = array(weight_np)
        g = array(grad)
        self._updater(key, g, w)
        # intentional sync: PS weights are host-resident numpy by design
        # (the server's optimizer IS host compute, not a wire stall)
        self._weights[key] = w.asnumpy()  # lint: disable=host-sync-on-hot-path


def main():
    import argparse

    # The PS is host-side by design (reference ps-lite servers are CPU
    # processes): pin jax to cpu BEFORE any NDArray is created, or the
    # optimizer's first _apply would initialize the accelerator backend —
    # and hang forever when the axon tunnel is down (observed 2026-07-30:
    # every push RPC then times out). MXNET_PS_PLATFORM overrides.
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("MXNET_PS_PLATFORM", "cpu"))

    ap = argparse.ArgumentParser(description="mxnet_tpu async parameter server")
    ap.add_argument("--port", type=int, default=9091)
    ap.add_argument("--num-workers", type=int, default=1)
    ap.add_argument("--snapshot-dir", default=None,
                    help="durable-state directory (atomic+CRC snapshots; "
                    "warm restart picks up the newest valid one). Falls "
                    "back to MXNET_PS_SNAPSHOT_DIR")
    ap.add_argument("--snapshot-period", type=float, default=None,
                    help="seconds between snapshots "
                    "(MXNET_PS_SNAPSHOT_PERIOD_S, default 5)")
    args = ap.parse_args()
    srv = PSServer(port=args.port, num_workers=args.num_workers,
                   snapshot_dir=args.snapshot_dir,
                   snapshot_period=args.snapshot_period)
    print(f"PSServer listening on :{srv.port}", flush=True)
    srv.serve_forever()


if __name__ == "__main__":
    main()
