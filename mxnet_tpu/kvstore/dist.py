"""Distributed KVStore: multi-process sync over jax.distributed + async ZMQ PS.

Reference: ``src/kvstore/kvstore_dist.h`` / ``kvstore_dist_server.h`` over
ps-lite (TBV — SURVEY.md §3.4, §5.8 transport 3).

TPU-native redesign:

- ``dist_sync`` / ``dist_device_sync``: each process is a jax.distributed
  worker; push/pull map to a global-sum collective over the DCN/ICI mesh via
  ``jax.make_array_from_process_local_data`` + psum (multi-host pjit subsumes
  per-key RPC). Environment mirrors the reference launcher contract:
  DMLC_NUM_WORKER / DMLC_WORKER_ID (or MXNET_COORDINATOR for jax.distributed).
- ``dist_async``: a literal host-side parameter server over ZMQ-style TCP
  (pure-stdlib socket framing; C++ server planned) — workers push grads, the
  server applies the optimizer on arrival, workers pull fresh weights with no
  barrier. See mxnet_tpu/kvstore/ps_server.py.
"""
from __future__ import annotations

import os

from ..base import MXNetError, get_env
from .kvstore import KVStore, _as_list

__all__ = ["DistKVStore"]


class DistKVStore(KVStore):
    """Multi-process kvstore. Sync modes use collectives; async uses the PS."""

    def __init__(self, kind="dist_sync"):
        super().__init__(kind)
        self._is_async = "async" in kind
        self._rank = int(get_env("DMLC_WORKER_ID", get_env("MXNET_WORKER_ID", 0, int), int) or 0)
        self._num_workers = int(get_env("DMLC_NUM_WORKER", get_env("MXNET_NUM_WORKER", 1, int), int) or 1)
        self._ps = None
        if self._is_async:
            addr = get_env("MXNET_PS_ADDR", get_env("DMLC_PS_ROOT_URI", None))
            port = int(get_env("MXNET_PS_PORT", get_env("DMLC_PS_ROOT_PORT", 9091, int), int) or 9091)
            if addr:
                from .ps_client import PSClient

                self._ps = PSClient(addr, port)
        else:
            self._maybe_init_jax_distributed()

    def _maybe_init_jax_distributed(self):
        if self._num_workers <= 1:
            return
        import jax

        coord = get_env("MXNET_COORDINATOR", None)
        if coord and jax.process_count() == 1:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=self._num_workers,
                                       process_id=self._rank)

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def push(self, key, value, priority=0):
        if self._ps is not None:
            keys, values = _as_list(key), _as_list(value)
            for k, v in zip(keys, values):
                vs = _as_list(v)
                merged = vs[0]
                for e in vs[1:]:
                    merged = merged + e
                self._ps.push(str(k), merged.asnumpy())
            return
        if self._num_workers > 1:
            # sum across processes via a psum on the global mesh
            keys, values = _as_list(key), _as_list(value)
            for k, v in zip(keys, values):
                vs = _as_list(v)
                merged = vs[0]
                for e in vs[1:]:
                    merged = merged + e
                reduced = _cross_process_sum(merged)
                super().push(str(k), reduced)
            return
        super().push(key, value, priority)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if self._ps is not None:
            keys, outs = _as_list(key), _as_list(out)
            for k, o in zip(keys, outs):
                arr = self._ps.pull(str(k))
                for oo in _as_list(o):
                    from ..ndarray import array

                    oo._set_data(array(arr)._data)
            return
        super().pull(key, out=out, priority=priority)

    def set_optimizer(self, optimizer):
        if self._ps is not None:
            self._ps.set_optimizer(optimizer)
            return
        super().set_optimizer(optimizer)

    def init(self, key, value):
        if self._ps is not None:
            keys, values = _as_list(key), _as_list(value)
            for k, v in zip(keys, values):
                self._ps.init(str(k), v.asnumpy())
            return
        super().init(key, value)

    def barrier(self):
        if self._ps is not None:
            self._ps.barrier()
            return
        if self._num_workers > 1:
            import jax
            import jax.numpy as jnp

            # an effectful collective barrier: global sum of a scalar
            _cross_process_sum_scalar()


def _cross_process_sum(nd_arr):
    """Sum an identical-shaped array across jax processes (DCN allreduce)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.process_count() == 1:
        return nd_arr
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices()).reshape(jax.process_count(), -1)[:, :1].reshape(-1)
    mesh = Mesh(devs, ("w",))
    local = nd_arr.asjax()[None]
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("w")), np.asarray(local))

    @jax.jit
    def reduce_fn(x):
        return jnp.sum(x, axis=0)

    out = reduce_fn(garr)
    from ..ndarray import NDArray

    return NDArray(jax.device_get(out))


def _cross_process_sum_scalar():
    import jax
    import numpy as np

    from ..ndarray import array

    _cross_process_sum(array(np.zeros(1, np.float32)))
