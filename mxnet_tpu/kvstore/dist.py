"""Distributed KVStore: multi-process sync over jax.distributed + async TCP PS.

Reference: ``src/kvstore/kvstore_dist.h`` / ``kvstore_dist_server.h`` over
ps-lite (TBV — SURVEY.md §3.4, §5.8 transport 3).

TPU-native redesign:

- ``dist_sync`` / ``dist_device_sync``: each process is a jax.distributed
  worker; push maps to a global-sum collective over the DCN mesh
  (``jax.make_array_from_process_local_data`` + an all-reduce jit). One
  1-device-per-process mesh is built once and reused for every key/step, so
  each (shape, dtype) compiles exactly once. Environment mirrors the
  reference launcher contract: ``DMLC_NUM_WORKER`` / ``DMLC_WORKER_ID`` and
  ``MXNET_COORDINATOR`` (or ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``) —
  all set by ``tools/launch.py``.
- ``dist_async``: a literal host-side parameter server over plain TCP —
  workers push grads, the server applies the optimizer on arrival, workers
  pull fresh weights with no barrier (native/ps/ps_server.cc or the python
  twin mxnet_tpu/kvstore/ps_server.py).
- **elastic** ``dist_sync`` (``MXNET_ELASTIC=1`` + a PS address, see
  docs/ROBUSTNESS.md "Elastic training"): the sync reduction rides the PS
  wire as a generation-scoped allreduce (``kvstore/elastic.py``) instead
  of a jax.distributed collective, so a SIGKILL'd worker releases — not
  wedges — every barrier, survivors recut the data shards at the next
  epoch boundary, and a restarted worker rejoins from the shared
  checkpoint. Also the only multi-process sync transport on backends
  without multiprocess collectives (the CPU backend, notably).

Create the kvstore before touching any jax arrays: ``jax.distributed``
must initialize before the local backend is first used (same
create-kvstore-first ordering the reference launcher assumes).
"""
from __future__ import annotations

import os

from .. import obs
from ..base import MXNetError, get_env
from . import elastic as elastic_mod
from .kvstore import KVStore, _as_list

__all__ = ["DistKVStore", "hierarchical_allreduce"]


def hierarchical_allreduce(session, key: str, flat, group_size: int,
                           round_id: int, part: int, nparts: int,
                           packer=None):
    """Group-tree sum over the elastic wire (docs/ROBUSTNESS.md
    "Asynchronous training"): three scoped reduces instead of one
    all-to-one round —

      1. group-local sum on ``key@g<gid>`` (``group_size`` contributors;
         ``packer`` may 2-bit-compress this widest stage's wire bytes —
         the dtype-16 framing from kvstore/compression.py, which the
         server dequantizes on arrival),
      2. cross-group sum on ``key@x`` (leaders only, one per group, with
         each group's contributor count riding as an extra element),
      3. broadcast back on ``key@b<gid>`` (the leader contributes the
         fleet total, everyone else zeros).

    ``round_id`` is the caller's explicit per-key counter: leaders run
    one more scoped round than non-leaders, so the session's flat
    ``_round`` cannot pace these. Returns ``(summed, contributors)``.
    Raises :class:`~mxnet_tpu.kvstore.elastic.ElasticError` on a stage
    timeout (a mid-round death) — callers fall back to the flat reduce.
    """
    import numpy as np

    flat = np.ascontiguousarray(np.asarray(flat, np.float32).ravel())
    G = max(2, int(group_size))
    gid, lane = part // G, part % G
    ngroups = (nparts + G - 1) // G
    gsize = max(1, min(G, nparts - gid * G))
    payload = packer(flat) if packer is not None else None
    gsum, n1 = session.allreduce_scoped(f"{key}@g{gid}", flat, gsize,
                                        round_id, payload=payload)
    gsum = np.asarray(gsum, np.float32)
    if lane == 0:
        # the group's contributor count rides the cross-group vector so
        # stage 3 can hand every rank the fleet-total divisor
        ext = np.concatenate([gsum, np.float32([n1])])
        xsum, _nx = session.allreduce_scoped(f"{key}@x", ext, ngroups,
                                             round_id)
        bcast_in = np.asarray(xsum, np.float32)
    else:
        bcast_in = np.zeros(flat.size + 1, np.float32)
    total, _nb = session.allreduce_scoped(f"{key}@b{gid}", bcast_in,
                                          gsize, round_id)
    total = np.asarray(total, np.float32)
    return total[:-1], max(1, int(round(float(total[-1]))))


class DistKVStore(KVStore):
    """Multi-process kvstore. Sync modes use collectives; async uses the PS."""

    def __init__(self, kind="dist_sync"):
        super().__init__(kind)
        self._is_async = "async" in kind
        self._rank = int(get_env("DMLC_WORKER_ID", get_env("MXNET_WORKER_ID", 0, int), int) or 0)
        self._num_workers = int(get_env("DMLC_NUM_WORKER", get_env("MXNET_NUM_WORKER", 1, int), int) or 1)
        self._ps = None
        self._mesh = None
        self._gc = None
        self._elastic = None
        self._batch = {}  # pending local merges awaiting the fused collective
        # bounded-staleness async session state (docs/ROBUSTNESS.md
        # "Asynchronous training"): MXNET_ASYNC_STALENESS opts in — the
        # committed step this rank last pushed (OP_CLOCK), the fleet
        # clock bounds cached off every clock/pull reply, and the
        # staleness-aware lr compensation toggle. Worker-side scaling
        # (not server-side) keeps the WAL replay byte-exact.
        env = get_env("MXNET_ASYNC_STALENESS", None)
        self._async_staleness = int(env) if env is not None else None
        self._async_step = 0
        self._clock_floor = 0
        self._clock_max = 0
        self._clock_widen = 0
        self._lr_comp = str(get_env("MXNET_ASYNC_LR_COMP", "1")).lower() \
            not in ("0", "false", "")
        # hierarchical reduction: group size (0/1 = flat), per-key round
        # counters — leaders run one extra scoped round per step, so the
        # session's flat counter cannot pace the tree stages
        self._hier_group = get_env("MXNET_ASYNC_GROUP", 0, int) or 0
        self._hier_rounds = {}
        addr = get_env("MXNET_PS_ADDR", get_env("DMLC_PS_ROOT_URI", None))
        port = int(get_env("MXNET_PS_PORT", get_env("DMLC_PS_ROOT_PORT", 9091, int), int) or 9091)
        if self._is_async:
            if addr:
                from .ps_client import PSClient

                self._ps = PSClient(addr, port)
        elif elastic_mod.elastic_enabled() and addr:
            # elastic dist_sync: reductions over the PS wire, scoped to the
            # live membership generation (docs/ROBUSTNESS.md). Joining here
            # (kvstore-creation time) keeps the reference's create-first
            # ordering; a restarted worker lands quarantined and Module.fit
            # resolves the rejoin at the next epoch boundary.
            self._elastic = elastic_mod.ElasticWorkerSession(
                addr, port, rank=self._rank, expected=self._num_workers)
            self._elastic.ensure_joined()
            # this process IS fleet rank r: pin the training-fleet step
            # accounting and the straggler injector to it (both fall back
            # to DMLC_WORKER_ID, but launchers aren't the only entry)
            from ..chaos import slow as _chaos_slow
            from ..obs import fleetstats as _fleetstats

            _fleetstats.set_rank(self._rank)
            _chaos_slow.set_rank(self._rank)
        else:
            self._maybe_init_jax_distributed()

    def _maybe_init_jax_distributed(self):
        if self._num_workers <= 1:
            return
        import jax

        coord = get_env("MXNET_COORDINATOR", None)
        if not coord:
            uri = get_env("DMLC_PS_ROOT_URI", None)
            port = get_env("DMLC_PS_ROOT_PORT", None)
            if uri and port:
                coord = f"{uri}:{port}"
        if not coord:
            raise MXNetError(
                "dist_sync needs MXNET_COORDINATOR (or DMLC_PS_ROOT_URI + "
                "DMLC_PS_ROOT_PORT) — launch through tools/launch.py")
        # NB: can't guard with jax.process_count() — that call would itself
        # initialize the backend before distributed init.
        try:
            initialized = jax.distributed.is_initialized()
        except AttributeError:  # older jax: fall back to the private state
            try:
                from jax._src import distributed as _jax_dist

                initialized = _jax_dist.global_state.client is not None
            except (ImportError, AttributeError):
                initialized = False
        if not initialized:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=self._num_workers,
                                       process_id=self._rank)

    def _dcn_mesh(self):
        """One device per process, built once (SURVEY §5.8: DCN allreduce)."""
        if self._mesh is None:
            import numpy as np
            import jax
            from jax.sharding import Mesh

            devs = (np.array(jax.devices())
                    .reshape(jax.process_count(), -1)[:, :1].reshape(-1))
            self._mesh = Mesh(devs, ("worker",))
        return self._mesh

    def _allreduce(self, nd_arr, bcast_from=None):
        """Global sum (or broadcast of one rank's value) across processes."""
        import numpy as np
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ndarray import NDArray

        if self._elastic is not None:
            local = np.asarray(nd_arr.asnumpy())
            if bcast_from is not None and self._rank != bcast_from:
                local = np.zeros_like(local)
            summed, _n = self._elastic.allreduce("__allreduce__", local)
            return NDArray(np.asarray(summed, local.dtype).reshape(
                local.shape))
        if self._num_workers <= 1 or jax.process_count() == 1:
            return nd_arr
        mesh = self._dcn_mesh()
        local = np.asarray(nd_arr.asnumpy())[None]
        if bcast_from is not None and self._rank != bcast_from:
            local = np.zeros_like(local)
        garr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("worker")), local)
        out = _sum_over_workers(garr, mesh)
        return NDArray(np.asarray(jax.device_get(out)))

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    @property
    def elastic(self):
        """The :class:`~mxnet_tpu.kvstore.elastic.ElasticWorkerSession` in
        elastic dist_sync mode, else None. ``Module.fit`` keys its elastic
        hooks (quarantined rejoin, grad sync, epoch rendezvous + shard
        recut) off this."""
        return self._elastic

    def step_complete(self, step: int):
        """Commit "this rank FINISHED ``step``" to the PS committed-clock
        table (``OP_CLOCK``) — the worker half of the bounded-staleness
        protocol. ``Module.fit`` calls it after every optimizer step; a
        no-op outside async-staleness mode. The ack carries the fleet
        clock bounds, so this is also where the lr-compensation lag and
        the gate's floor view refresh."""
        if self._ps is None or self._async_staleness is None:
            return
        self._async_step = int(step)
        floor, maxc, widen = self._ps.push_clock(self._rank, int(step))
        self._clock_floor, self._clock_max = floor, maxc
        self._clock_widen = widen
        if obs.enabled():
            obs.set_gauge("kvstore.async.clock_floor", floor)
            obs.set_gauge(f"kvstore.async.rank{self._rank}_lag",
                          max(0, maxc - int(step)))

    def _lr_comp_scale(self) -> float:
        """Staleness-aware lr compensation (worker-side so the server's
        WAL replay stays byte-exact): a gradient computed ``lag`` steps
        behind the fleet's fastest committed clock is scaled by
        ``1 / (1 + lag)`` — stale directions count less, the async run's
        effective step size tracks the sync run's."""
        if self._async_staleness is None or not self._lr_comp:
            return 1.0
        lag = max(0, self._clock_max - self._async_step)
        return 1.0 / (1.0 + lag)

    def _fused_flat_reduce(self, arrays, key: str, zero_local: bool):
        """One fused sum-reduction of many arrays: flatten-concat, reduce
        over the fleet (elastic generation-scoped reduce or the jax
        collective), split back. ``zero_local`` contributes zeros (the
        broadcast idiom: the sum is then the sole contributor's values).
        Returns ``(summed_arrays, contributors)``."""
        import numpy as np

        shapes = [a.shape for a in arrays]
        sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]
        flat = np.concatenate(
            [np.asarray(a, np.float32).ravel() for a in arrays]) \
            if arrays else np.zeros(0, np.float32)
        if zero_local:
            flat = np.zeros_like(flat)
        if self._elastic is not None:
            summed, n = self._elastic_reduce(key, flat)
        else:
            from ..ndarray import NDArray

            summed = self._allreduce(NDArray(flat)).asnumpy()
            n = self._num_workers
        summed = np.asarray(summed, np.float32)
        out, off = [], 0
        for shape, size in zip(shapes, sizes):
            out.append(summed[off:off + size].reshape(shape))
            off += size
        return out, n

    def _elastic_reduce(self, key: str, flat):
        """One elastic sum: the group-tree (``MXNET_ASYNC_GROUP`` > 1 and
        a fleet larger than one group) or the flat generation-scoped
        reduce. A tree-stage timeout (a mid-round death desyncs the
        scoped contributor counts until the next epoch recut) falls back
        to the flat reduce, which is membership-scoped and releases over
        the survivors — degraded shape, same numerics."""
        joined = getattr(self._elastic, "_joined", None)
        if (self._hier_group > 1 and joined is not None
                and joined.num_parts > self._hier_group):
            rid = self._hier_rounds.get(key, 0)
            self._hier_rounds[key] = rid + 1
            try:
                return hierarchical_allreduce(
                    self._elastic, key, flat, self._hier_group, rid,
                    joined.part_index, joined.num_parts)
            except elastic_mod.StaleMemberError:
                raise
            except elastic_mod.ElasticError:
                obs.inc("kvstore.hier.fallbacks")
                obs.event("kvstore.hier.fallback", key=key, round=rid)
        return self._elastic.allreduce(key, flat)

    def allreduce_mean(self, arrays):
        """Mean-allreduce a list of numpy arrays over the LIVE fleet in one
        fused reduction. Returns ``(means, contributors)``. Under
        elasticity the divisor is the count that actually contributed —
        when a worker dies mid-epoch the survivors' gradient *scale* stays
        a mean, it just averages fewer shards (documented tolerance in
        docs/ROBUSTNESS.md)."""
        summed, n = self._fused_flat_reduce(arrays, "__grads__",
                                            zero_local=False)
        return [s / max(1, n) for s in summed], n

    def broadcast_arrays(self, arrays, root: bool):
        """One fused broadcast over the live fleet: the root's values win
        (non-roots contribute zeros to the sum-reduce — the
        ``_allreduce(bcast_from=)`` idiom). Used by the elastic fit's
        initial-parameter sync so differently-initialized ranks can never
        silently train divergent models."""
        out, _n = self._fused_flat_reduce(arrays, "__bcast__",
                                          zero_local=not root)
        return out

    def close(self):
        """Leave the fleet cleanly (elastic mode): deregisters this worker
        so the membership generation bumps now instead of after K missed
        heartbeats."""
        if self._elastic is not None:
            self._elastic.close()
            self._elastic = None

    def init(self, key, value):
        if self._ps is not None:
            keys, values = _as_list(key), _as_list(value)
            for k, v in zip(keys, values):
                self._ps.init(str(k), v.asnumpy())
            return
        if self._num_workers > 1:
            # reference semantics: rank 0's init value wins on the server
            keys, values = _as_list(key), _as_list(value)
            for k, v in zip(keys, values):
                super().init(str(k), self._allreduce(v, bcast_from=0))
            return
        super().init(key, value)

    def set_gradient_compression(self, compression_params):
        from .compression import (GradientCompression,
                                  validate_compression_params)

        params = validate_compression_params(compression_params)
        self._gc = (GradientCompression(params["threshold"])
                    if params else None)
        self._compression = params

    def push(self, key, value, priority=0):
        if self._ps is not None:
            from ..ndarray.sparse import RowSparseNDArray

            keys, values = _as_list(key), _as_list(value)
            for k, v in zip(keys, values):
                vs = _as_list(v)
                if all(isinstance(e, RowSparseNDArray) for e in vs):
                    # sparse wire: concatenated (indices, rows) — the server
                    # scatter-merges; only touched rows cross the DCN
                    import numpy as np

                    idx = np.concatenate(
                        [e.indices.asnumpy().astype(np.int32) for e in vs])
                    rows = np.concatenate([e.data.asnumpy() for e in vs])
                    self._ps.push_row_sparse(str(k), idx, rows)
                    continue
                merged = vs[0]
                for e in vs[1:]:
                    merged = merged + e
                arr = merged.asnumpy()
                scale = self._lr_comp_scale()
                if scale != 1.0:
                    arr = arr * scale
                    obs.inc("kvstore.async.lr_comp_applied")
                self._ps.push(str(k), arr,
                              compressor=getattr(self, "_gc", None))
            return
        if self._num_workers > 1:
            # Lazy batched push (reference PSKV bulk execution analog): local
            # merges buffer here; ONE fused collective moves every pending
            # key at the next pull/barrier instead of a host round-trip per
            # key. A push never pulled is only applied at the next flush
            # point — pull before exiting.
            keys, values = _as_list(key), _as_list(value)
            for k, v in zip(keys, values):
                vs = _as_list(v)
                merged = vs[0]
                for e in vs[1:]:
                    merged = merged + e
                k = str(k)
                if k in self._batch:
                    self._batch[k] = self._batch[k] + merged
                else:
                    self._batch[k] = merged
            if self._updater is not None:
                # optimizer-on-store: each push must be its own optimizer
                # step (merging two pushes into one would change momentum/
                # Adam numerics vs the reference's per-push server update)
                self._flush_batch()
            return
        super().push(key, value, priority)

    def _flush_batch(self):
        """Fused allreduce of every pending key: grads concatenate into one
        flat vector (uint8-packed when 2-bit compression is on — the wire
        actually shrinks 16x, unlike round 2's quantize-then-dequantize),
        cross one collective, and split back."""
        if not self._batch:
            return
        import numpy as np

        from ..ndarray import NDArray

        items = [(k, v) for k, v in self._batch.items()]
        self._batch = {}
        gc = getattr(self, "_gc", None)
        shapes = [v.shape for _, v in items]
        dtypes = [v.dtype for _, v in items]
        sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]
        if gc is None:
            flat = np.concatenate(
                [v.asnumpy().astype(np.float32).ravel() for _, v in items])
            summed = self._allreduce(NDArray(flat)).asnumpy()
        else:
            packs = [gc.compress(k, v.asnumpy()) for k, v in items]
            pack_lens = [p.size for p in packs]
            summed_full = self._allgather_sum_packed(
                np.concatenate(packs), gc.threshold)
            segs = []
            off = 0
            for plen, size in zip(pack_lens, sizes):
                segs.append(summed_full[off * 4: off * 4 + size])
                off += plen
            summed = np.concatenate(segs)
        off = 0
        for (k, _v), shape, dt, size in zip(items, shapes, dtypes, sizes):
            part = summed[off:off + size].reshape(shape).astype(dt)
            off += size
            super().push(k, NDArray(part))

    def _allgather_sum_packed(self, packed: "np.ndarray", threshold: float):
        """All-gather each worker's packed 2-bit codes (uint8, size/4 bytes
        on the wire) and decode+sum them in one jitted program per worker."""
        import numpy as np

        if self._elastic is not None:
            # elastic transport: decode the local codes and sum the floats
            # through the generation-scoped reduce (same numerics — the
            # quantization/error-feedback already happened in compress())
            from .compression import dequantize_2bit

            decoded = np.asarray(
                dequantize_2bit(packed, threshold, packed.size * 4),
                np.float32)
            summed, _n = self._elastic.allreduce("__packed__", decoded)
            return np.asarray(summed, np.float32)
        if self._num_workers <= 1:
            from .compression import dequantize_2bit

            return dequantize_2bit(packed, threshold, packed.size * 4)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if jax.process_count() == 1:
            from .compression import dequantize_2bit

            return dequantize_2bit(packed, threshold, packed.size * 4)
        mesh = self._dcn_mesh()
        garr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("worker")), packed[None])
        out = _packed_sum_for(mesh, float(threshold))(garr)
        return np.asarray(jax.device_get(out))

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if self._ps is not None:
            import numpy as np

            from ..ndarray import array

            keys, outs, rids = _as_list(key), _as_list(out), _as_list(row_ids)
            for k, o, r in zip(keys, outs, rids):
                idx = (r.asnumpy() if hasattr(r, "asnumpy")
                       else np.asarray(r)).astype(np.int32)
                rows = self._ps.pull_row_sparse(str(k), idx)
                for oo in _as_list(o):
                    oo._set_data(array(rows)._data)
            return
        self._flush_batch()
        super().row_sparse_pull(key, out=out, priority=priority,
                                row_ids=row_ids)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if self._ps is not None:
            keys, outs = _as_list(key), _as_list(out)
            for k, o in zip(keys, outs):
                if self._async_staleness is not None:
                    # staleness-gated: blocks server-side while this rank
                    # would run more than s (+ policy widening) steps
                    # ahead of the fleet's committed-clock floor
                    arr, floor, maxc = self._ps.pull_stale(
                        str(k), self._rank, self._async_step,
                        self._async_staleness)
                    self._clock_floor, self._clock_max = floor, maxc
                else:
                    arr = self._ps.pull(str(k))
                for oo in _as_list(o):
                    from ..ndarray import array

                    oo._set_data(array(arr)._data)
            return
        self._flush_batch()
        super().pull(key, out=out, priority=priority)

    def set_optimizer(self, optimizer):
        if self._ps is not None:
            self._ps.set_optimizer(optimizer)
            return
        super().set_optimizer(optimizer)

    def barrier(self):
        if self._ps is not None:
            self._ps.barrier()
            return
        self._flush_batch()
        if self._elastic is not None:
            # generation-scoped: the server counts LIVE members, so a dead
            # rank releases the rendezvous over the survivors
            self._elastic.barrier()
            return
        if self._num_workers > 1:
            import numpy as np

            from ..ndarray import array

            self._allreduce(array(np.zeros(1, np.float32)))


import functools


@functools.lru_cache(maxsize=None)
def _packed_sum_for(mesh, threshold):
    """jit per (mesh, threshold): decode each worker's 2-bit row and sum.
    The collective moves uint8 (all_gather via sharding propagation) —
    1 byte per 4 gradient values on the DCN."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def decode_sum(packed):  # (W, L) uint8
        crumbs = jnp.stack([packed & 3, (packed >> 2) & 3,
                            (packed >> 4) & 3, (packed >> 6) & 3], axis=-1)
        vals = jnp.where(crumbs == 1, jnp.float32(threshold),
                         jnp.where(crumbs == 2, jnp.float32(-threshold),
                                   jnp.float32(0)))
        return vals.reshape(vals.shape[0], -1).sum(axis=0)

    return jax.jit(decode_sum, out_shardings=NamedSharding(mesh, P()))


@functools.lru_cache(maxsize=None)
def _reducer_for(mesh):
    """One jitted reduce per mesh; jax then caches one program per shape."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.jit(lambda x: jnp.sum(x, axis=0),
                   out_shardings=NamedSharding(mesh, P()))


def _sum_over_workers(garr, mesh):
    return _reducer_for(mesh)(garr)
