"""Deterministic straggler injection — delay a named rank's step phase at
counted occurrences.

The training-fleet telemetry plane (obs/fleetstats.py) promises: a rank
lagging the fleet is *detected* (StragglerDetector verdict within K
windows) and *blamed* (the lagging phase named). None of that is provable
unless a straggler can be injected deterministically — so, the chaos
idiom: a rule names a rank and a step phase, and fires a fixed sleep on
exact 1-based occurrence counts of that phase completing on that rank.
The flagged rank and the blamed phase must then match the injection
(tests/test_fleetstats.py flagship).

The delay fires INSIDE the phase's span (obs/fleetstats.py ``_PhaseCtx``),
so the injected lag is visible on the merged timeline as exactly the
stretched phase the detector blames.

Configuration
-------------
Programmatic (tests): ``configure([Rule(1, "forward", {5, 6}, 0.25)])``
then ``reset()``. Env (subprocesses): ``MXNET_CHAOS_SLOW`` as semicolon-
separated ``rank:phase@occs:seconds`` — occurrences are 1-based counts of
that (rank, phase) pair, given as a comma list and/or ``lo-hi`` ranges;
empty means every occurrence. Examples::

    MXNET_CHAOS_SLOW="1:forward@5-40:0.25"    # rank 1, forwards 5..40
    MXNET_CHAOS_SLOW="0:data_wait::0.1"       # rank 0, every data_wait
    MXNET_CHAOS_SLOW="2:update@3,7:0.5"       # rank 2, 3rd and 7th update

The seconds field also takes a ramp form ``base+step``: the delay starts
at ``base`` on the rule's first matching occurrence and grows by ``step``
per occurrence after it — a *worsening* straggler, so staleness-widening
policies can be proven against deterioration, not just a constant lag::

    MXNET_CHAOS_SLOW="1:forward@5-40:0.1+0.02"  # 0.1s at occ 5, +0.02/occ

The rank is resolved from :func:`set_rank` (the elastic session calls it
with the fleet rank) falling back to ``DMLC_WORKER_ID``. When the env var
is unset the hook costs one truthiness check (fleetstats gates on the raw
env string before importing this module at all).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set

from .. import obs

__all__ = ["Rule", "configure", "reset", "enabled", "maybe_delay",
           "set_rank", "parse_env"]


class Rule:
    def __init__(self, rank: int, phase: str,
                 occurrences: Optional[Set[int]] = None,
                 seconds: float = 0.0, ramp: float = 0.0):
        self.rank = int(rank)
        self.phase = phase
        self.occurrences = set(occurrences) if occurrences else None
        self.seconds = float(seconds)
        # per-occurrence growth (the ``base+step`` env form): a worsening
        # straggler instead of a constant one
        self.ramp = float(ramp)

    def delay_for(self, occ: int) -> float:
        """Injected seconds at 1-based occurrence ``occ``: constant, or
        ``base + (occ - first_occurrence) * ramp`` for a ramp rule."""
        if not self.ramp:
            return self.seconds
        first = min(self.occurrences) if self.occurrences else 1
        return self.seconds + max(0, occ - first) * self.ramp

    def __repr__(self):
        occ = sorted(self.occurrences) if self.occurrences else "all"
        secs = (f"{self.seconds}+{self.ramp}" if self.ramp
                else f"{self.seconds}")
        return f"SlowRule(rank{self.rank}:{self.phase}@{occ}:{secs}s)"


class _State(threading.local):
    """Thread-local counters (the RPC-chaos idiom): concurrent step loops
    in one test must not race each other's occurrence counts."""

    def __init__(self):
        self.rules: Optional[List[Rule]] = None
        self.counters: Dict[int, int] = {}


_STATE = _State()
_PROGRAMMATIC: Optional[List[Rule]] = None
_RANK: Optional[int] = None


def set_rank(r: int) -> None:
    """Pin this process's fleet rank (the elastic session calls it); the
    ``DMLC_WORKER_ID`` env var is the fallback."""
    global _RANK
    _RANK = int(r)


def _rank() -> int:
    if _RANK is not None:
        return _RANK
    return int(os.environ.get(
        "DMLC_WORKER_ID", os.environ.get("MXNET_WORKER_ID", 0)) or 0)


def _parse_occs(spec: str) -> Optional[Set[int]]:
    if not spec:
        return None
    out: Set[int] = set()
    for tok in filter(None, (t.strip() for t in spec.split(","))):
        lo, dash, hi = tok.partition("-")
        if dash:
            out.update(range(int(lo), int(hi) + 1))
        else:
            out.add(int(tok))
    return out or None


def parse_env(spec: str) -> List[Rule]:
    rules = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        fields = part.split(":")
        # canonical rank:phase@occs:seconds; an empty occurrence list may
        # be written rank:phase:seconds (or rank:phase::seconds)
        if len(fields) == 4 and fields[2] == "":
            fields = [fields[0], fields[1], fields[3]]
        if len(fields) != 3:
            raise ValueError(f"bad MXNET_CHAOS_SLOW entry {part!r} "
                             "(want rank:phase@occs:seconds)")
        rank_s, phase_occ, seconds = fields
        phase, _, occs = phase_occ.partition("@")
        if not phase:
            raise ValueError(f"bad MXNET_CHAOS_SLOW entry {part!r}")
        try:
            # ramp form base+step (a worsening straggler); plain floats —
            # including exponent notation like 1e+3 — stay constant rules
            base_s, plus, step_s = seconds.partition("+")
            ramp = 0.0
            if plus and base_s and step_s:
                try:  # both halves must parse, else (e.g. "1e+3") it's
                    _base, ramp = float(base_s), float(step_s)  # constant
                except ValueError:
                    ramp = 0.0
                else:
                    seconds = base_s
            rules.append(Rule(int(rank_s), phase, _parse_occs(occs),
                              float(seconds), ramp=ramp))
        except ValueError as e:
            raise ValueError(
                f"bad MXNET_CHAOS_SLOW entry {part!r}: {e}") from e
    return rules


def configure(rules: List[Rule]) -> None:
    global _PROGRAMMATIC
    _PROGRAMMATIC = list(rules)
    _STATE.rules = None
    _STATE.counters = {}


def reset() -> None:
    global _PROGRAMMATIC, _RANK
    _PROGRAMMATIC = None
    _RANK = None
    _STATE.rules = None
    _STATE.counters = {}


def _active_rules() -> List[Rule]:
    if _PROGRAMMATIC is not None:
        return _PROGRAMMATIC
    if _STATE.rules is None:
        spec = os.environ.get("MXNET_CHAOS_SLOW", "")
        _STATE.rules = parse_env(spec) if spec else []
    return _STATE.rules


def enabled() -> bool:
    return bool(_active_rules())


def maybe_delay(phase: str) -> float:
    """Hook at the end of a step phase on this rank: sleeps (and tags the
    injection in the same timeline the step writes to) when a rule
    matches this (rank, phase) at this occurrence. Returns the injected
    seconds (0.0 when nothing fired)."""
    rules = _active_rules()
    if not rules:
        return 0.0
    my_rank = _rank()
    injected = 0.0
    for rule in rules:
        if rule.rank != my_rank or rule.phase != phase:
            continue
        key = id(rule)
        _STATE.counters[key] = _STATE.counters.get(key, 0) + 1
        occ = _STATE.counters[key]
        if rule.occurrences is not None and occ not in rule.occurrences:
            continue
        secs = rule.delay_for(occ)
        obs.event("chaos.slow", rank=my_rank, phase=phase,
                  occurrence=occ, seconds=secs)
        obs.inc("chaos.injected")
        obs.inc("chaos.slow.injected")
        time.sleep(secs)
        injected += secs
    return injected
