"""NaN fault injection — poison a named tensor at a counted occurrence.

The training-health plane (obs/health.py) promises: a non-finite blowup is
*detected* (sentinel breach), *attributed* (the blame pass names the first
non-finite op), and *recovered from* (auto-rollback to the last valid
checkpoint, bitwise-reproducible replay). None of that is testable unless a
NaN can be injected deterministically — so, the chaos idiom: a rule names a
tensor and fires on exact 1-based occurrence counts of that tensor being
bound into an Executor forward. Occurrence counting is what makes the
flagship test's replay clean: the rollback re-runs the poisoned batch, the
occurrence is already consumed, the retried segment is bitwise identical to
an uninjected run.

Configuration
-------------
Programmatic (tests): ``configure([Rule("data", {5})])`` then ``reset()``.
Env (subprocesses): ``MXNET_CHAOS_NAN`` as semicolon-separated
``tensor@occ1,occ2`` — e.g. ``MXNET_CHAOS_NAN="data@5"`` poisons the 5th
forward's ``data`` input. An empty occurrence list means every occurrence.
Only float tensors can be poisoned (an int tensor matches but is skipped
with a warning — NaN has no integer encoding).

The hook (``executor.Executor.forward``) costs one module-level ``enabled()``
check when no rules are installed — the chaos contract.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set

from .. import obs

__all__ = ["Rule", "configure", "reset", "enabled", "poison", "parse_env"]


class Rule:
    def __init__(self, tensor: str, occurrences: Optional[Set[int]] = None):
        self.tensor = tensor
        self.occurrences = set(occurrences) if occurrences else None

    def __repr__(self):
        occ = sorted(self.occurrences) if self.occurrences else "all"
        return f"NanRule({self.tensor}@{occ})"


class _State(threading.local):
    """Thread-local counters (the RPC-chaos idiom): concurrent executors in
    one test must not race each other's occurrence counts."""

    def __init__(self):
        self.rules: Optional[List[Rule]] = None
        self.counters: Dict[int, int] = {}


_STATE = _State()
_PROGRAMMATIC: Optional[List[Rule]] = None


def parse_env(spec: str) -> List[Rule]:
    rules = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        tensor, _, occs = part.partition("@")
        if not tensor:
            raise ValueError(f"bad MXNET_CHAOS_NAN entry {part!r}")
        occurrences = ({int(o) for o in occs.split(",") if o}
                       if occs else None)
        rules.append(Rule(tensor, occurrences))
    return rules


def configure(rules: List[Rule]) -> None:
    global _PROGRAMMATIC
    _PROGRAMMATIC = list(rules)
    _STATE.rules = None
    _STATE.counters = {}


def reset() -> None:
    global _PROGRAMMATIC
    _PROGRAMMATIC = None
    _STATE.rules = None
    _STATE.counters = {}


def _active_rules() -> List[Rule]:
    if _PROGRAMMATIC is not None:
        return _PROGRAMMATIC
    if _STATE.rules is None:
        spec = os.environ.get("MXNET_CHAOS_NAN", "")
        _STATE.rules = parse_env(spec) if spec else []
    return _STATE.rules


def enabled() -> bool:
    return bool(_active_rules())


def poison(names, values) -> list:
    """Given parallel (names, device values) about to enter a forward,
    return values with any matching tensors poisoned (element 0 → NaN).
    Call only after ``enabled()`` — the hot path pays one check."""
    import jax.numpy as jnp
    import numpy as np

    rules = _active_rules()
    out = list(values)
    if not rules:
        return out
    by_name = {}
    for i, n in enumerate(names):
        by_name[n] = i
    for rule in rules:
        i = by_name.get(rule.tensor)
        if i is None:
            continue
        key = id(rule)
        _STATE.counters[key] = _STATE.counters.get(key, 0) + 1
        occ = _STATE.counters[key]
        if rule.occurrences is not None and occ not in rule.occurrences:
            continue
        v = out[i]
        dtype = np.dtype(str(getattr(v, "dtype", "float32")))
        if not (np.issubdtype(dtype, np.floating)
                or str(dtype) == "bfloat16"):
            import warnings

            warnings.warn(f"MXNET_CHAOS_NAN: tensor {rule.tensor!r} has "
                          f"non-float dtype {dtype} — not poisoned")
            continue
        arr = jnp.asarray(v)
        flat = jnp.ravel(arr).at[0].set(jnp.nan)
        out[i] = flat.reshape(arr.shape)
        # tagged in the SAME timeline as the breach / blame / rollback it
        # will cause — the whole fault experiment reads as one story
        obs.event("chaos.nan", tensor=rule.tensor, occurrence=occ)
        obs.inc("chaos.injected")
        obs.inc("chaos.nan.injected")
    return out
