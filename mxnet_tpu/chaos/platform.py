"""Platform-outage injection: simulate a hung accelerator tunnel.

Round 5 lost every BENCH/MULTICHIP artifact to one dead axon tunnel —
``jax.devices()`` blocked forever in every driver (NOTES_r05.md). The fix
(``mxnet_tpu.platform``) wraps those choke points in a watchdog; this
injector makes the failure reproducible on demand so the degradation path
(bounded exit + parseable ``platform_unavailable`` artifact) is a tested
contract, not a hope.

``MXNET_CHAOS_TUNNEL_HANG`` names the guard points to hang:

- ``1`` / ``all`` / ``*`` — every guarded platform call blocks;
- a comma list (e.g. ``jax.devices,device_put``) — only those points.

The hook runs *inside* the watchdog's worker thread and blocks it forever
(a daemon thread, so it dies with the process) — byte-for-byte the shape of
the real outage: the caller sees no exception, no return, nothing, until
the watchdog fires. Like every injector in this package it is one env
lookup when disabled.
"""
from __future__ import annotations

import os
import time
from typing import Optional, Set

__all__ = ["hang_points", "hang_if_injected"]

_ALL = {"1", "all", "*", "true", "yes"}


def hang_points() -> Optional[Set[str]]:
    """Parsed ``MXNET_CHAOS_TUNNEL_HANG``: None when off, ``{"*"}`` for
    every point, else the set of guard-point names to hang. Parsed per
    call — subprocess tests flip the env var at runtime."""
    spec = os.environ.get("MXNET_CHAOS_TUNNEL_HANG", "").strip()
    if not spec:
        return None
    if spec.lower() in _ALL:
        return {"*"}
    return {p.strip() for p in spec.split(",") if p.strip()}


def hang_if_injected(point: str) -> None:
    """Block forever if chaos targets this guard point (called from inside
    the platform watchdog's worker thread)."""
    pts = hang_points()
    if pts is None or ("*" not in pts and point not in pts):
        return
    from .. import obs

    obs.event("chaos.tunnel_hang", point=point)
    while True:  # the real outage never returns either
        time.sleep(3600)
