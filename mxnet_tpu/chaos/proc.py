"""Process-level fault injection: SIGKILL at named code points or at a
chosen training step.

Kill points
-----------
Production code calls ``kill_point("name")`` at interesting instants (the
checkpoint writer brackets its commit with ``ckpt:post_arrays`` /
``ckpt:pre_rename`` / ``ckpt:post_rename``). With
``MXNET_CHAOS_KILL="ckpt:pre_rename@3"`` the process SIGKILLs itself the 3rd
time that point is reached — no cleanup handlers run, exactly like a
preempted VM vanishing. Comma-separate multiple entries; omit ``@n`` to die
on the first hit. When the env var is unset the hook is one dict lookup.

Step-targeted kills
-------------------
:func:`run_until_step` launches a training subprocess that prints
``CHAOS_STEP <n>`` markers (tools/chaos_kill.py does) and SIGKILLs it the
moment step N is reported — the flagship elastic-training test kills
mid-epoch and asserts a resumed run is bitwise identical to an uninterrupted
one.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import List, Optional, Tuple

__all__ = ["kill_point", "reset_kill_points", "run_until_step",
           "corrupt_file", "STEP_MARKER"]

STEP_MARKER = "CHAOS_STEP"

_counters: dict = {}
_parsed: Optional[dict] = None


def _plan() -> dict:
    global _parsed
    if _parsed is None:
        plan = {}
        for part in filter(None, os.environ.get("MXNET_CHAOS_KILL",
                                                "").split(",")):
            point, _, occ = part.strip().partition("@")
            plan[point] = int(occ) if occ else 1
        _parsed = plan
    return _parsed


def reset_kill_points() -> None:
    global _parsed
    _parsed = None
    _counters.clear()


def kill_point(name: str) -> None:
    """SIGKILL this process if MXNET_CHAOS_KILL targets the Nth hit of
    ``name``. SIGKILL, not sys.exit: atexit/finally must not run — a real
    preemption doesn't unwind the stack either."""
    plan = _plan()
    if not plan or name not in plan:
        return
    _counters[name] = _counters.get(name, 0) + 1
    if _counters[name] == plan[name]:
        # last words to the telemetry stream: the ring buffer dies with the
        # process, but a JSONL stream (MXNET_OBS_JSONL) is flushed per
        # event, so the kill shows up in the post-mortem timeline
        from .. import obs

        obs.event("chaos.kill", point=name, occurrence=_counters[name])
        os.kill(os.getpid(), signal.SIGKILL)


# ---------------------------------------------------------------------------
# subprocess orchestration
# ---------------------------------------------------------------------------

def run_until_step(cmd: List[str], kill_at_step: int, env: Optional[dict] = None,
                   timeout: float = 300.0,
                   marker: str = STEP_MARKER) -> Tuple[int, str]:
    """Run ``cmd``, SIGKILL it when its stdout reports ``<marker> N`` with
    N >= kill_at_step. Returns (returncode, collected stdout). -SIGKILL as
    the returncode confirms the kill landed; any other code means the run
    finished before reaching the step (the caller should assert on this).
    """
    import threading

    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    lines: List[str] = []
    killed = False
    timed_out = threading.Event()

    def _expire():
        # the read loop blocks in readline(); a victim that hangs without
        # output would block the harness forever without this watchdog
        timed_out.set()
        proc.kill()

    watchdog = threading.Timer(timeout, _expire)
    watchdog.start()
    try:
        assert proc.stdout is not None
        for line in proc.stdout:
            lines.append(line)
            if not killed and line.startswith(marker):
                try:
                    step = int(line.split()[1])
                except (IndexError, ValueError):
                    continue
                if step >= kill_at_step:
                    os.kill(proc.pid, signal.SIGKILL)
                    killed = True
        proc.wait(timeout=60)
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)
    if timed_out.is_set():
        raise TimeoutError(
            f"run_until_step timed out:\n{''.join(lines[-50:])}")
    return proc.returncode, "".join(lines)


def run_to_completion(cmd: List[str], env: Optional[dict] = None,
                      timeout: float = 300.0) -> Tuple[int, str]:
    """Run ``cmd`` to completion, returning (returncode, stdout+stderr)."""
    out = subprocess.run(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True, env=env,
                         timeout=timeout)
    return out.returncode, out.stdout


def corrupt_file(path: str, offset: int = -8, flip: int = 0xFF) -> None:
    """Flip bits of one byte in ``path`` (negative offset = from the end).
    The CRC layers must catch this."""
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        pos = offset if offset >= 0 else size + offset
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ flip]))


def main(argv=None):  # pragma: no cover - thin CLI shim
    from . import __doc__ as chaos_doc

    print(chaos_doc)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
