"""RPC fault injection for the parameter-server client.

``kvstore/ps_client.py`` calls :func:`on_send` before writing a frame and
:func:`on_reply` after reading one. Rules match an opcode name and fire on
exact 1-based occurrence counts of that (op, action) pair, so a test can say
"drop the reply of the 2nd PUSH_SEQ" and get that, every run.

Actions
-------
- ``drop_request``: raise ConnectionError *before* the frame is sent — the
  server never sees it (models a lost request packet).
- ``drop_reply``: raise ConnectionError *after* the reply was read — the
  server HAS processed the RPC, the client believes it failed (models a lost
  ack; the retry is where at-least-once becomes double-apply unless the
  server dedups).
- ``delay``: sleep ``seconds`` before sending (models congestion; lets a test
  restart the server during an in-flight RPC).
- ``dup``: send the frame twice back-to-back (models a duplicating network);
  the client drains both replies.

Configuration
-------------
Programmatic (tests): ``configure([Rule("push_seq", "drop_reply", {1})])``
then ``reset()``. Env (subprocesses): ``MXNET_CHAOS_RPC`` as semicolon-
separated ``op:action@occ1,occ2[:seconds]`` — e.g.
``MXNET_CHAOS_RPC="push_seq:drop_reply@1;pull:delay@2:0.5"``. An empty
occurrence list means every occurrence.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set

from .. import obs

__all__ = ["Rule", "configure", "reset", "on_send", "on_reply", "enabled"]

# opcode value -> canonical rule name (mirrors kvstore/ps_server.py opcodes).
# The serving plane (opcodes 32+) registers its names here on import —
# serve/server.py does OP_NAMES.update(SERVE_OP_NAMES), ONE source of truth —
# so one rule table fault-injects both training and inference RPCs.
OP_NAMES = {0: "init", 1: "push", 2: "pull", 3: "set_opt", 4: "barrier",
            5: "shutdown", 6: "push_sparse", 7: "pull_sparse", 8: "push_seq",
            9: "push_sparse_seq"}

_SEND_ACTIONS = ("drop_request", "delay", "dup")
_REPLY_ACTIONS = ("drop_reply",)


class ChaosConnectionError(ConnectionError):
    """Marks an injected fault (subclass of what the retry path catches)."""


class Rule:
    def __init__(self, op: str, action: str, occurrences: Optional[Set[int]] = None,
                 seconds: float = 0.0):
        if action not in _SEND_ACTIONS + _REPLY_ACTIONS:
            raise ValueError(f"unknown chaos action {action!r}")
        self.op = op.lower()
        self.action = action
        self.occurrences = set(occurrences) if occurrences else None
        self.seconds = float(seconds)

    def __repr__(self):
        occ = sorted(self.occurrences) if self.occurrences else "all"
        return f"Rule({self.op}:{self.action}@{occ})"


class _State(threading.local):
    """Thread-local so concurrent client threads in one test can't race the
    counters; env parsing happens once per thread on first use."""

    def __init__(self):
        self.rules: Optional[List[Rule]] = None
        self.counters: Dict[int, int] = {}  # id(rule) -> match count


_STATE = _State()
_PROGRAMMATIC: Optional[List[Rule]] = None


def parse_env(spec: str) -> List[Rule]:
    rules = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        fields = part.split(":")
        if len(fields) not in (2, 3):
            raise ValueError(f"bad MXNET_CHAOS_RPC entry {part!r}")
        op, action_occ = fields[0], fields[1]
        seconds = float(fields[2]) if len(fields) == 3 else 0.0
        action, _, occs = action_occ.partition("@")
        occurrences = ({int(o) for o in occs.split(",") if o}
                       if occs else None)
        rules.append(Rule(op, action, occurrences, seconds))
    return rules


def configure(rules: List[Rule]) -> None:
    """Install rules for this process (all threads); resets counters."""
    global _PROGRAMMATIC
    _PROGRAMMATIC = list(rules)
    _STATE.rules = None
    _STATE.counters = {}


def reset() -> None:
    global _PROGRAMMATIC
    _PROGRAMMATIC = None
    _STATE.rules = None
    _STATE.counters = {}


def _active_rules() -> List[Rule]:
    if _PROGRAMMATIC is not None:
        return _PROGRAMMATIC
    if _STATE.rules is None:
        spec = os.environ.get("MXNET_CHAOS_RPC", "")
        _STATE.rules = parse_env(spec) if spec else []
    return _STATE.rules


def enabled() -> bool:
    return bool(_active_rules())


def _fire(rule: Rule, opname: str) -> bool:
    # keyed per RULE, not per (op, action): two rules targeting the same
    # op+action at different occurrences must each count every matching
    # event exactly once, or occurrence specs drift nondeterministically
    key = id(rule)
    _STATE.counters[key] = _STATE.counters.get(key, 0) + 1
    fired = (rule.occurrences is None
             or _STATE.counters[key] in rule.occurrences)
    if fired:
        # tag the injection in the SAME timeline the training step writes
        # to, so a fault experiment reads as "RPC span, then chaos.rpc
        # event, then the retry" instead of an invisible stall
        obs.event("chaos.rpc", action=rule.action, op=opname,
                  occurrence=_STATE.counters[key],
                  seconds=rule.seconds or None)
        obs.inc("chaos.injected")
        obs.inc(f"chaos.rpc.{rule.action}")
    return fired


def on_send(opcode: int, key: str) -> Optional[str]:
    """Hook before a frame is sent. Raises to drop the request, sleeps to
    delay it, or returns "dup" to ask the client to send it twice."""
    rules = _active_rules()
    if not rules:
        return None
    opname = OP_NAMES.get(opcode, str(opcode))
    verdict = None
    for rule in rules:
        if rule.op != opname or rule.action not in _SEND_ACTIONS:
            continue
        if not _fire(rule, opname):
            continue
        if rule.action == "drop_request":
            raise ChaosConnectionError(
                f"chaos: dropped {opname} request (key={key!r})")
        if rule.action == "delay":
            time.sleep(rule.seconds)
        elif rule.action == "dup":
            verdict = "dup"
    return verdict


def on_reply(opcode: int, key: str) -> None:
    """Hook after a reply was read. Raising here models a lost ack: the
    server applied the RPC but the client will retry it."""
    rules = _active_rules()
    if not rules:
        return
    opname = OP_NAMES.get(opcode, str(opcode))
    for rule in rules:
        if rule.op != opname or rule.action not in _REPLY_ACTIONS:
            continue
        if _fire(rule, opname):
            raise ChaosConnectionError(
                f"chaos: dropped {opname} reply (key={key!r})")
