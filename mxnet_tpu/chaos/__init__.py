"""Deterministic fault injection for robustness testing (``pytest -m chaos``).

Two injector families, both env-gated so production code paths cost one dict
lookup when chaos is off:

- :mod:`mxnet_tpu.chaos.rpc` — drop / delay / duplicate parameter-server
  RPCs at exact occurrence counts (``MXNET_CHAOS_RPC`` or programmatic
  rules). Hooks live in ``kvstore/ps_client.py``.
- :mod:`mxnet_tpu.chaos.proc` — SIGKILL the current process at named code
  points (``MXNET_CHAOS_KILL``, e.g. the checkpoint writer mid-rename), and
  helpers to run a training subprocess and kill it at a chosen step.

Determinism is the point: a chaos test that flakes is worse than no test.
Every injector fires on a counted occurrence of a named event, never on a
timer or a random draw.
"""
from __future__ import annotations

from . import proc, rpc

__all__ = ["rpc", "proc"]
