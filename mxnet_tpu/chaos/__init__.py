"""Deterministic fault injection for robustness testing (``pytest -m chaos``).

Two injector families, both env-gated so production code paths cost one dict
lookup when chaos is off:

- :mod:`mxnet_tpu.chaos.rpc` — drop / delay / duplicate parameter-server
  RPCs at exact occurrence counts (``MXNET_CHAOS_RPC`` or programmatic
  rules). Hooks live in ``kvstore/ps_client.py``.
- :mod:`mxnet_tpu.chaos.proc` — SIGKILL the current process at named code
  points (``MXNET_CHAOS_KILL``, e.g. the checkpoint writer mid-rename or a
  serve replica's ``serve:pre_reply``), and helpers to run a training
  subprocess and kill it at a chosen step. The serving fleet
  (``serve/fleet.py``) forwards ``MXNET_CHAOS_KILL_REPLICA<i>`` to replica
  *i* as its ``MXNET_CHAOS_KILL``, so one env var SIGKILLs exactly one
  member of a fleet at a named point.
- :mod:`mxnet_tpu.chaos.platform` — hang the guarded platform entry points
  (``MXNET_CHAOS_TUNNEL_HANG``) the way a dead accelerator tunnel does, so
  every driver's bounded-exit + platform-error-artifact path is testable.
- :mod:`mxnet_tpu.chaos.nan` — poison a named tensor with NaN at a counted
  occurrence of it entering an Executor forward (``MXNET_CHAOS_NAN``), so
  the training-health plane's detection → provenance → auto-rollback chain
  (obs/health.py) is deterministically testable end to end.
- :mod:`mxnet_tpu.chaos.slow` — delay a named rank's step phase at counted
  occurrences (``MXNET_CHAOS_SLOW``), so the training-fleet straggler
  detector (obs/fleetstats.py) is chaos-proven: the flagged rank and the
  blamed phase must match the injection.

Determinism is the point: a chaos test that flakes is worse than no test.
Every injector fires on a counted occurrence of a named event, never on a
timer or a random draw.
"""
from __future__ import annotations

from . import nan, platform, proc, rpc, slow

__all__ = ["rpc", "proc", "platform", "nan", "slow"]
