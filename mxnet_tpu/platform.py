"""Guarded platform entry points — outage-proof access to the accelerator.

Round 5's postmortem (NOTES_r05.md, ROADMAP item 3): the axon tunnel went
dark and ``jax.devices()`` blocked *forever* inside every driver —
``bench.py``, the MULTICHIP dry run, every ``tools/`` probe — so the round
shipped zero valid artifacts and no error either. The reference stack never
had this failure mode (ps-lite treats a dead peer as a timeout); a
TPU-native framework has to build the equivalent discipline at the PJRT
boundary.

This module is that boundary. Every first touch of the platform —
enumeration, backend init, the first ``device_put`` — goes through a
**watchdog**: the call runs in a daemon worker thread, the caller waits at
most ``timeout`` seconds, and a hang becomes a raised
:class:`PlatformUnavailable` carrying a machine-parseable artifact. Drivers
then degrade instead of hanging:

- ``devices_or_exit()`` prints ONE JSON line (schema
  ``mxnet_tpu.platform_error/1``) and exits non-zero in bounded time — the
  driver's capture records a *valid* "platform_unavailable" artifact;
- ``__graft_entry__.dryrun_multichip`` falls back to the virtual CPU mesh
  (the child needs no tunnel — round 5's exact missed save);
- the serving fleet keeps serving on the replicas that still answer.

Chaos twin: ``MXNET_CHAOS_TUNNEL_HANG`` (``chaos/platform.py``) blocks the
worker thread exactly like the real outage, so the bounded-exit contract is
asserted by tests, not assumed.

``MXNET_PLATFORM_TIMEOUT`` overrides the default watchdog budget
(seconds); per-call ``timeout=`` wins over both.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, List, Optional

from .base import MXNetError

__all__ = ["PlatformUnavailable", "call_with_watchdog", "devices",
           "devices_or_exit", "device_put", "emit_artifact",
           "virtual_cpu_env", "ARTIFACT_SCHEMA", "default_timeout"]

ARTIFACT_SCHEMA = "mxnet_tpu.platform_error/1"


def default_timeout() -> float:
    """Watchdog budget in seconds (``MXNET_PLATFORM_TIMEOUT``, default 90
    — comfortably under the 120 s bound every driver must exit within)."""
    return float(os.environ.get("MXNET_PLATFORM_TIMEOUT", 90))


class PlatformUnavailable(MXNetError):
    """A guarded platform call hung past its watchdog (``kind =
    "platform_unavailable"`` — the tunnel-outage signature) or raised
    during backend init (``kind = "platform_init_failed"`` — a real
    plugin/config failure that must not be triaged as the known hang)."""

    def __init__(self, what: str, detail: str, *, kind: str,
                 timeout_s: float, elapsed_s: float):
        super().__init__(f"{kind}: {what}: {detail}")
        self.what = what
        self.detail = detail
        self.kind = kind
        self.timeout_s = timeout_s
        self.elapsed_s = elapsed_s

    def artifact(self, **extra: Any) -> dict:
        """The machine-parseable error record every driver emits — one
        schema, so the capture harness greps for a single shape."""
        out = {
            "schema": ARTIFACT_SCHEMA,
            "error": self.kind,
            "what": self.what,
            "detail": self.detail[:300],
            "timeout_s": round(self.timeout_s, 1),
            "elapsed_s": round(self.elapsed_s, 1),
            "pid": os.getpid(),
            "argv0": os.path.basename(sys.argv[0]) if sys.argv else "",
        }
        if self.kind == "platform_unavailable":
            out["hint"] = ("accelerator tunnel unresponsive — platform "
                           "outage, not a framework failure (see "
                           "NOTES_r05.md / BASELINE.md escalation log)")
        out.update(extra)
        return out


def call_with_watchdog(fn: Callable[[], Any], *, what: str,
                       timeout: Optional[float] = None) -> Any:
    """Run ``fn()`` in a daemon worker thread, waiting at most ``timeout``
    seconds. A hang raises :class:`PlatformUnavailable` (the worker thread
    is abandoned — it blocks on a dead tunnel and dies with the process,
    which is the only safe treatment PJRT offers); an exception from ``fn``
    is re-raised as ``platform_init_failed`` with the original message."""
    from .chaos.platform import hang_if_injected

    budget = default_timeout() if timeout is None else float(timeout)
    result: List[Any] = []
    error: List[BaseException] = []

    def _run():
        try:
            hang_if_injected(what)  # chaos: the blocking enumeration hook
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 — reported via the artifact
            error.append(e)

    t0 = time.monotonic()
    worker = threading.Thread(target=_run, daemon=True,
                              name=f"mxtpu-platform-watchdog[{what}]")
    worker.start()
    worker.join(timeout=budget)
    elapsed = time.monotonic() - t0
    if worker.is_alive():
        raise PlatformUnavailable(
            what, f"no response within {budget:g}s watchdog",
            kind="platform_unavailable", timeout_s=budget, elapsed_s=elapsed)
    if error:
        e = error[0]
        raise PlatformUnavailable(
            what, f"{type(e).__name__}: {e}", kind="platform_init_failed",
            timeout_s=budget, elapsed_s=elapsed) from e
    return result[0]


def devices(timeout: Optional[float] = None, backend: Optional[str] = None):
    """``jax.devices()`` under the watchdog — the single most
    hang-prone call in the repo (it initializes the backend on first use,
    which is where a dead tunnel blocks forever)."""
    import jax

    return call_with_watchdog(
        lambda: jax.devices(backend) if backend else jax.devices(),
        what="jax.devices", timeout=timeout)


def device_put(x, device=None, timeout: Optional[float] = None):
    """First-touch-guarded ``jax.device_put``: probes in drivers route their
    opening upload through this so a tunnel that enumerates but no longer
    moves bytes still fails in bounded time. Steady-state transfers after a
    successful first touch stay unguarded (per-call watchdog threads would
    distort the numbers being measured)."""
    import jax

    return call_with_watchdog(lambda: jax.device_put(x, device),
                              what="device_put", timeout=timeout)


def emit_artifact(err: PlatformUnavailable, stream=None, **extra) -> dict:
    """Print the one-line JSON platform-error artifact (flushed — the
    process is usually about to exit) and return it."""
    art = err.artifact(**extra)
    print(json.dumps(art), file=stream or sys.stdout, flush=True)
    return art


def devices_or_exit(what: str = "", timeout: Optional[float] = None,
                    exit_code: int = 1, **extra):
    """Driver preamble: return the device list, or emit the parseable
    platform-error artifact and exit — a dead tunnel costs one watchdog
    budget, never a hung round. ``what`` names the driver in the artifact
    (defaults to argv[0])."""
    try:
        return devices(timeout=timeout)
    except PlatformUnavailable as e:
        if what:
            extra.setdefault("driver", what)
        emit_artifact(e, **extra)
        sys.exit(exit_code)


def virtual_cpu_env(n_devices: int, base: Optional[dict] = None) -> dict:
    """Child-process environment for an n-device virtual CPU mesh — the
    legal fallback when the real platform is unreachable (the CPU child
    needs no tunnel). The same recipe tests/conftest.py uses. Strips the
    tunnel-hang chaos injector: it simulates a *tunnel* fault, and the CPU
    child never touches the tunnel."""
    import re

    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
    env.pop("MXNET_CHAOS_TUNNEL_HANG", None)
    return env
