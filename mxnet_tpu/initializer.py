"""Weight initializers.

Reference: ``python/mxnet/initializer.py`` (TBV — SURVEY.md §2.3). Same
registry-by-name + ``InitDesc``-driven dispatch (names ending in _bias/_gamma/
_beta/_mean/_var get their conventional defaults).
"""
from __future__ import annotations

import math
import re

import numpy as np

from .random import np_rng

from .ndarray import NDArray, array

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal", "Orthogonal",
           "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "Mixed", "InitDesc",
           "Load", "create", "register"]

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(init, **kwargs) -> "Initializer":
    if init is None:
        return Uniform(0.07)
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        name = init.lower()
        if name not in _REGISTRY:
            raise ValueError(f"unknown initializer {init!r}; have {sorted(_REGISTRY)}")
        return _REGISTRY[name](**kwargs)
    raise TypeError(f"cannot create initializer from {init!r}")


class Initializer:
    """Base: callable on (name, NDArray) or dispatches by name suffix."""

    def __call__(self, name, arr: NDArray):
        if isinstance(name, NDArray):  # called as init(arr)
            self._init_weight("", name)
            return
        if name.endswith("bias") or name.endswith("beta") or name.endswith("mean"):
            arr[:] = 0.0
        elif name.endswith("gamma") or name.endswith("var"):
            arr[:] = 1.0
        else:
            self._init_weight(name, arr)

    def init_array(self, name, shape, dtype, ctx=None) -> NDArray:
        from .ndarray import zeros

        arr = zeros(shape, dtype=dtype, ctx=ctx)
        self(name, arr)
        return arr

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return self.__class__.__name__


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


_REGISTRY["zeros"] = Zero
_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = np_rng().uniform(-self.scale, self.scale, arr.shape).astype(arr.dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = np_rng().normal(0, self.sigma, arr.shape).astype(arr.dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np_rng().uniform(-1, 1, (nout, nin))
        else:
            tmp = np_rng().normal(0, 1, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q.reshape(arr.shape)).astype(arr.dtype)


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in, fan_out = shape[1] * hw if len(shape) > 1 else shape[0], shape[0] * hw
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        scale = math.sqrt(self.magnitude / max(factor, 1))
        if self.rnd_type == "uniform":
            arr[:] = np_rng().uniform(-scale, scale, shape).astype(arr.dtype)
        else:
            arr[:] = np_rng().normal(0, scale, shape).astype(arr.dtype)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        shape = arr.shape
        weight = np.zeros(shape, dtype=np.float32)
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.astype(arr.dtype)


@register
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        n = arr.shape[0] // 4
        b[n:2 * n] = self.forget_bias  # i, f, g, o gate order; forget slice
        arr[:] = b.astype(arr.dtype)


class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(f"parameter {name} did not match any pattern")


class InitDesc(str):
    """Parameter-name descriptor carrying init attrs (reference
    mx.init.InitDesc: a str subclass so name-suffix dispatch keeps
    working; ``attrs`` may carry __init__ overrides, ``global_init`` the
    fallback initializer)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


@register
class Load(Initializer):
    """Init from a saved param dict, falling back to ``default_init`` for
    missing names (reference mx.init.Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {}
        for k, v in dict(param).items():
            k = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k
            self.param[k] = v
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr: NDArray):
        if name in self.param:
            src = self.param[name]
            src_np = src.asnumpy() if isinstance(src, NDArray) else src
            if tuple(src_np.shape) != tuple(arr.shape):
                raise ValueError(
                    f"Load: shape mismatch for {name!r}: saved "
                    f"{src_np.shape} vs param {arr.shape}")
            arr[:] = src_np
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise ValueError(f"Load: no saved value for {name!r} and no "
                             "default_init")
