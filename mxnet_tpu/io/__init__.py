"""``mx.io`` — data iterators (reference python/mxnet/io/ + src/io/)."""
from .io import (DataBatch, DataDesc, DataIter, NDArrayIter, CSVIter,  # noqa: F401
                 MNISTIter, ImageRecordIter, ResizeIter, PrefetchingIter)
from . import recordio  # noqa: F401
