"""RecordIO — the reference's packed binary record format.

Reference: ``python/mxnet/recordio.py`` + dmlc-core RecordIO (TBV —
SURVEY.md §2.1). Format kept bit-compatible so .rec files interchange:

  [kMagic:u32][lrec:u32][data (padded to 4 bytes)] per record, where
  lrec's upper 3 bits encode continuation (unused here: cflag=0) and the
  lower 29 bits the payload length.

``IRHeader``/pack/unpack (flag, label, id, id2) match the reference's
image-record header. JPEG encode/decode goes through PIL instead of
OpenCV (no cv2 in this environment).
"""
from __future__ import annotations

import io as _io
import os
import struct
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_KMAGIC = 0xCED7230A

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential record file reader/writer (reference MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError(f"invalid flag {self.flag!r}")

    def close(self):
        if self.record:
            self.record.close()
            self.record = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["record"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf: bytes):
        assert self.writable
        self.record.write(struct.pack("<II", _KMAGIC, len(buf)))
        self.record.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        hdr = self.record.read(8)
        if len(hdr) < 8:
            return None
        magic, lrec = struct.unpack("<II", hdr)
        if magic != _KMAGIC:
            raise IOError(f"invalid RecordIO magic {magic:#x} in {self.uri}")
        length = lrec & ((1 << 29) - 1)
        buf = self.record.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.record.read(pad)
        return buf

    def tell(self):
        return self.record.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access record file via a .idx sidecar (reference analog)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.exists(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        k = self.key_type(parts[0])
                        self.idx[k] = int(parts[1])
                        self.keys.append(k)
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx:
            self.fidx.close()
            self.fidx = None
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a header + payload into one record blob (reference mx.recordio.pack)."""
    flag = header.flag
    label = header.label
    if isinstance(label, (list, tuple, np.ndarray)) and np.ndim(label) > 0:
        arr = np.asarray(label, np.float32)
        flag = arr.size
        hdr = struct.pack(_IR_FORMAT, flag, 0.0, header.id, header.id2)
        return hdr + arr.tobytes() + s
    hdr = struct.pack(_IR_FORMAT, 0, float(label), header.id, header.id2)
    return hdr + s


def unpack(s: bytes):
    """Unpack a record blob into (IRHeader, payload)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    payload = s[_IR_SIZE:]
    if flag > 0:
        arr = np.frombuffer(payload[:flag * 4], np.float32)
        return IRHeader(flag, arr, id_, id2), payload[flag * 4:]
    return IRHeader(flag, label, id_, id2), payload


def pack_img(header: IRHeader, img: np.ndarray, quality=95, img_fmt=".jpg") -> bytes:
    from PIL import Image

    buf = _io.BytesIO()
    arr = np.asarray(img, np.uint8)
    pil = Image.fromarray(arr.squeeze(-1) if arr.ndim == 3 and arr.shape[-1] == 1
                          else arr)
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    pil.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s: bytes, iscolor=1):
    from PIL import Image

    header, payload = unpack(s)
    img = Image.open(_io.BytesIO(payload))
    img = img.convert("RGB" if iscolor else "L")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return header, arr
