"""DataIter family: NDArrayIter / CSVIter / MNISTIter / ImageRecordIter.

Reference: ``python/mxnet/io/io.py`` + C++ iterators in ``src/io/``
(TBV — SURVEY.md §2.1 L8). The C++ threaded decode pipeline is replaced by
a thread-pool prefetcher (PrefetchingIter) feeding async PJRT transfers;
rank sharding keeps the reference's ``part_index``/``num_parts`` API.
"""
from __future__ import annotations

import os
import time
from collections import namedtuple
from typing import Dict, List, Optional

import numpy as np

from .. import obs
from ..ndarray import NDArray, array as nd_array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "MNISTIter", "ImageRecordIter", "ResizeIter", "PrefetchingIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)


class DataBatch:
    def __init__(self, data, label=None, pad=0, index=None, bucket_key=None,
                 provide_data=None, provide_label=None):
        self.data = data if isinstance(data, (list, tuple)) else [data]
        if label is None:
            self.label = []
        else:
            self.label = label if isinstance(label, (list, tuple)) else [label]
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __repr__(self):
        shapes = [d.shape for d in self.data]
        return f"DataBatch: data shapes {shapes} pad={self.pad}"


class DataIter:
    """Base iterator (reference mx.io.DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def __next__(self):
        return self.next()

    def next(self):
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(), self.getpad(),
                             self.getindex())
        raise StopIteration

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0

    # -- checkpoint/resume hooks (docs/ROBUSTNESS.md) ----------------------
    def get_checkpoint_state(self):
        """Snapshot of the iteration position for mid-epoch resume, or None
        when this iterator cannot be positioned (the fit loop then only
        checkpoints at epoch boundaries). Values must be JSON scalars or
        numpy arrays."""
        return None

    def set_checkpoint_state(self, state):
        raise NotImplementedError(
            f"{type(self).__name__} does not support mid-epoch resume")

    # -- elastic-training hook (docs/ROBUSTNESS.md "Elastic training") ----
    def set_partition(self, part_index, num_parts):
        """Recut this iterator's rank shard (``part_index`` of
        ``num_parts`` over the FULL dataset). Called at epoch boundaries
        when fleet membership changed — survivors absorb a dead worker's
        shard, a rejoiner takes its recut slice. Iterators that cannot be
        recut raise; the elastic fit loop treats that as
        "keep the construction-time shard"."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support shard recutting")


def _shard(arr, part_index, num_parts):
    if num_parts <= 1:
        return arr
    n = arr.shape[0]
    per = n // num_parts
    start = per * part_index + min(part_index, n % num_parts)
    end = start + per + (1 if part_index < n % num_parts else 0)
    return arr[start:end]


class NDArrayIter(DataIter):
    """Iterate numpy/NDArray tensors (reference NDArrayIter: pad/discard/
    roll_over last-batch handling, shuffle, optional rank sharding)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label",
                 part_index=0, num_parts=1):
        super().__init__(batch_size)
        # the FULL dataset is retained so elastic training can recut the
        # rank shard at an epoch boundary (set_partition); self.data/label
        # always hold the current shard's view
        self._full_data = _normalize(data, data_name)
        self._full_label = _normalize(label, label_name)
        self._shuffle = shuffle
        self._last = last_batch_handle
        self.part_index, self.num_parts = int(part_index), int(num_parts)
        self._apply_partition()
        if shuffle:
            np.random.shuffle(self._order)

    def _apply_partition(self):
        self.data = [(k, _shard(v, self.part_index, self.num_parts))
                     for k, v in self._full_data]
        self.label = [(k, _shard(v, self.part_index, self.num_parts))
                      for k, v in self._full_label]
        self.num_data = self.data[0][1].shape[0] if self.data else 0
        self.cursor = -self.batch_size
        self._order = np.arange(self.num_data)

    def set_partition(self, part_index, num_parts):
        """Recut the rank shard over the full dataset (elastic fit loops
        call this at epoch boundaries only — it rewinds the cursor and
        resets the shuffle order, which the next ``reset()`` reshuffles).

        Shards are trimmed to the EQUAL size ``n // num_parts`` (drop-last
        over the remainder): elastic sync is lockstep, so every live rank
        must run the same number of batches per epoch — and a user cannot
        pre-size a dataset divisibly for every possible surviving fleet
        size. At most ``num_parts - 1`` trailing samples sit out per
        epoch.

        Always recuts — even for an unchanged ``(part_index, num_parts)``:
        an iterator pre-sharded at construction keeps the remainder-
        unbalanced cut until this runs, and skipping the trim for it would
        quietly reintroduce the unequal batch counts."""
        self.part_index, self.num_parts = int(part_index), int(num_parts)
        self._apply_partition()
        total = self._full_data[0][1].shape[0] if self._full_data else 0
        even = total // max(1, self.num_parts)
        if self.num_data > even:
            self.data = [(k, v[:even]) for k, v in self.data]
            self.label = [(k, v[:even]) for k, v in self.label]
            self.num_data = even
            self._order = np.arange(even)
        if self._shuffle:
            np.random.shuffle(self._order)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self._shuffle:
            np.random.shuffle(self._order)
        if self._last == "roll_over" and 0 < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data)
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self._last == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrays):
        out = []
        for k, v in arrays:
            idx = self._order[max(self.cursor, 0):self.cursor + self.batch_size]
            part = v[idx]
            if part.shape[0] < self.batch_size and self._last == "pad":
                wrap = self._order[:self.batch_size - part.shape[0]]
                part = np.concatenate([part, v[wrap]], axis=0)
            out.append(nd_array(np.ascontiguousarray(part)))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if self._last == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def get_checkpoint_state(self):
        # cursor + shuffle order fully determine the remaining batches; the
        # global numpy RNG (next epoch's reshuffle) is captured separately
        # by the checkpoint RNG snapshot. The order MUST be copied: reset()
        # reshuffles it in place, and the snapshot may sit on the async
        # writer's queue across that
        return {"cursor": int(self.cursor),
                "order": np.array(self._order, np.int64)}

    def set_checkpoint_state(self, state):
        self.cursor = int(state["cursor"])
        self._order = np.asarray(state["order"], np.int64)


def _normalize(data, default_name) -> List:
    if data is None:
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = {default_name: data}
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}{i if i else ''}": d for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        v = np.asarray(v)
        if v.dtype == np.float64:
            v = v.astype(np.float32)
        out.append((k, v))
    return out


class CSVIter(DataIter):
    """CSV file iterator (reference src/io/iter_csv.cc analog)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, part_index=0, num_parts=1,
                 data_name="data", label_name="softmax_label"):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1 and len(label_shape) == 1:
                label = label.reshape(-1)
        else:
            label = np.zeros((data.shape[0],), np.float32)
        self._inner = NDArrayIter(
            {data_name: data}, {label_name: label}, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            data_name=data_name, label_name=label_name,
            part_index=part_index, num_parts=num_parts)
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def get_checkpoint_state(self):
        return self._inner.get_checkpoint_state()

    def set_checkpoint_state(self, state):
        self._inner.set_checkpoint_state(state)

    def set_partition(self, part_index, num_parts):
        self._inner.set_partition(part_index, num_parts)


class MNISTIter(DataIter):
    """MNIST IDX file iterator (reference src/io/iter_mnist.cc analog)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 part_index=0, num_parts=1, data_name="data",
                 label_name="softmax_label", **kwargs):
        from ..gluon.data.vision.datasets import _read_idx

        imgs = _read_idx(image).astype(np.float32) / 255.0
        lbls = _read_idx(label).astype(np.float32)
        imgs = imgs.reshape(-1, 784) if flat else imgs.reshape(-1, 1, 28, 28)
        self._inner = NDArrayIter({data_name: imgs}, {label_name: lbls},
                                  batch_size=batch_size, shuffle=shuffle,
                                  last_batch_handle="discard",
                                  data_name=data_name, label_name=label_name,
                                  part_index=part_index, num_parts=num_parts)
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def get_checkpoint_state(self):
        return self._inner.get_checkpoint_state()

    def set_checkpoint_state(self, state):
        self._inner.set_checkpoint_state(state)

    def set_partition(self, part_index, num_parts):
        self._inner.set_partition(part_index, num_parts)


class ImageRecordIter(DataIter):
    """Image RecordIO iterator with decode + augment + batch (the reference's
    C++ ImageRecordIter pipeline: src/io/iter_image_recordio_2.cc — TBV).

    Decode/augment runs in a thread pool (PIL releases the GIL for JPEG
    work); supports rank sharding and basic augmentations used by the
    ImageNet configs (resize, rand_crop, rand_mirror, mean/std, HWC→CHW).
    """

    def __init__(self, path_imgrec, data_shape, batch_size=1, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False, resize=-1,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, part_index=0, num_parts=1, preprocess_threads=4,
                 round_batch=True, data_name="data", label_name="softmax_label",
                 path_imgidx=None, dtype="float32", **kwargs):
        super().__init__(batch_size)
        from .recordio import MXIndexedRecordIO, MXRecordIO, unpack_img

        # dtype="uint8" is the TPU-first fast path: raw pixels cross the
        # host→device link (4x smaller) and mean/std normalization fuses
        # into the jitted train step (see parallel.ShardedTrainer preprocess;
        # .mean/.std expose the deferred constants).
        if dtype not in ("float32", "uint8"):
            raise ValueError(f"dtype must be float32|uint8, got {dtype!r}")
        self.dtype = dtype
        self._unpack_img = unpack_img
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        self._mean = np.array([mean_r, mean_g, mean_b], np.float32).reshape(3, 1, 1)
        self._std = np.array([std_r, std_g, std_b], np.float32).reshape(3, 1, 1)
        self.mean, self.std = self._mean, self._std  # public for fused normalize
        self._shuffle = shuffle
        self._threads = max(1, int(preprocess_threads))

        idx_path = path_imgidx or os.path.splitext(path_imgrec)[0] + ".idx"
        if os.path.exists(idx_path):
            rec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
            keys = list(rec.keys)
            self._rec = rec
            self._offsets = [rec.idx[k] for k in keys]
        else:
            # no index: scan once for record offsets
            rec = MXRecordIO(path_imgrec, "r")
            self._offsets = []
            while True:
                pos = rec.tell()
                if rec.read() is None:
                    break
                self._offsets.append(pos)
            self._rec = rec
        self._full_offsets = np.asarray(self._offsets)
        self.part_index, self.num_parts = int(part_index), int(num_parts)
        self._offsets = _shard(self._full_offsets, part_index, num_parts)
        self._order = np.arange(len(self._offsets))
        self.cursor = 0
        if shuffle:
            np.random.shuffle(self._order)
        import threading

        self._read_lock = threading.Lock()  # seek+read on the shared handle
        self._path = path_imgrec
        self._native = None
        # The C++ pipeline decodes RGB only; grayscale/other channel counts
        # go through the PIL fallback which honors data_shape[0].
        if not kwargs.get("no_native") and self.data_shape[0] == 3:
            from ..native import io_lib

            self._native = io_lib()  # C++ decode pipeline when built
        self._seed_counter = 0

    @property
    def provide_data(self):
        dt = np.uint8 if self.dtype == "uint8" else np.float32
        return [DataDesc("data", (self.batch_size,) + self.data_shape, dt)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self.cursor = 0
        if self._shuffle:
            np.random.shuffle(self._order)

    def set_partition(self, part_index, num_parts):
        """Recut the record-offset shard (elastic epoch boundary); under the
        read lock because prefetch workers may still be draining. Shards
        are trimmed to the equal ``n // num_parts`` size (drop-last) so
        every live rank runs the same batch count — the lockstep-reduce
        invariant. Always recuts (see NDArrayIter.set_partition: a
        construction-time shard is remainder-unbalanced until trimmed)."""
        with self._read_lock:
            self.part_index, self.num_parts = int(part_index), int(num_parts)
            self._offsets = _shard(self._full_offsets, self.part_index,
                                   self.num_parts)
            even = len(self._full_offsets) // max(1, self.num_parts)
            if len(self._offsets) > even:
                self._offsets = self._offsets[:even]
            self._order = np.arange(len(self._offsets))
            self.cursor = 0
        if self._shuffle:
            np.random.shuffle(self._order)

    def _load_one(self, offset, rng=None):
        rng = rng if rng is not None else np.random
        with self._read_lock:  # decode below stays parallel; IO is serialized
            self._rec.record.seek(offset)
            blob = self._rec.read()
        header, img = self._unpack_img(blob, iscolor=1)  # HWC uint8
        c, h, w = self.data_shape
        if self._resize > 0:
            img = _resize_short(img, self._resize)
        if self._rand_crop:
            img = _rand_crop(img, h, w, rng)
        else:
            img = _center_crop(img, h, w)
        if self._rand_mirror and rng.rand() < 0.5:
            img = img[:, ::-1]
        if self.dtype == "uint8":
            chw = img.transpose(2, 0, 1)
        else:
            chw = img.astype(np.float32).transpose(2, 0, 1)
            chw = (chw - self._mean) / self._std
        label = header.label
        if np.ndim(label) == 0:
            label = np.float32(label)
        else:
            label = np.asarray(label, np.float32)[:self.label_width]
        return chw, label

    def _advance(self):
        """Reserve the next batch's record offsets + augmentation seed
        (thread-safe): the cursor/seed state mutates under the read lock so
        PrefetchingIter can run several _load_batch calls concurrently
        (decode on one worker overlapping the host→device transfer of
        another) without racing the cursor, the deterministic seed
        counter, or the global RNG."""
        with self._read_lock:
            n = len(self._offsets)
            if self.cursor + self.batch_size > n:
                raise StopIteration
            idxs = self._order[self.cursor:self.cursor + self.batch_size]
            self.cursor += self.batch_size
            self._seed_counter += 1
            if self._rand_crop or self._rand_mirror:
                seed = int(np.random.randint(0, 2 ** 31))
            else:
                seed = self._seed_counter
        return [int(self._offsets[i]) for i in idxs], seed

    def _load_batch(self, reserved):
        offsets, seed = reserved
        if self._native is not None:
            try:
                return self._next_native(offsets, seed)
            except RuntimeError:
                self._native = None  # e.g. PNG records → PIL fallback
        import concurrent.futures as cf

        # per-image RandomStates derived from the batch's reserved seed:
        # the PIL fallback stays deterministic per (seed, position) even
        # with concurrent prefetch workers (no global-RNG races). Skipped
        # entirely when nothing draws randomness (MT19937 init per image
        # is measurable on the 1-core host).
        if self._rand_crop or self._rand_mirror:
            rngs = [np.random.RandomState((seed + 31 * i) % (2 ** 31))
                    for i in range(len(offsets))]
        else:
            rngs = [None] * len(offsets)
        if self._threads > 1:
            with cf.ThreadPoolExecutor(self._threads) as pool:
                results = list(pool.map(self._load_one, offsets, rngs))
        else:
            results = [self._load_one(o, r) for o, r in zip(offsets, rngs)]
        data = np.stack([r[0] for r in results])
        label = np.stack([r[1] for r in results])
        return DataBatch([nd_array(data)], [nd_array(label)], 0, None)

    def next(self):
        return self._load_batch(self._advance())

    def _next_native(self, offsets, seed=None):
        """Batch decode through the C++ pipeline (native/io/recordio_jpeg.cc)."""
        import ctypes

        bs = len(offsets)
        c, h, w = self.data_shape
        labels = np.empty((bs, self.label_width), np.float32)
        offs = (ctypes.c_int64 * bs)(*offsets)
        if seed is None:  # direct callers; _advance() reserves it otherwise
            self._seed_counter += 1
            seed = (int(np.random.randint(0, 2 ** 31))
                    if (self._rand_crop or self._rand_mirror)
                    else self._seed_counter)
        if self.dtype == "uint8":
            data = np.empty((bs, 3, h, w), np.uint8)
            fails = self._native.mxtpu_decode_batch_u8(
                self._path.encode(), offs, bs, h, w, int(self._resize),
                int(bool(self._rand_crop)), int(bool(self._rand_mirror)),
                ctypes.c_uint64(seed),
                data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self.label_width, self._threads)
        else:
            data = np.empty((bs, 3, h, w), np.float32)
            mean = (ctypes.c_float * 3)(*self._mean.ravel())
            std = (ctypes.c_float * 3)(*self._std.ravel())
            fails = self._native.mxtpu_decode_batch(
                self._path.encode(), offs, bs, h, w, int(self._resize),
                int(bool(self._rand_crop)), int(bool(self._rand_mirror)),
                ctypes.c_uint64(seed), mean, std,
                data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self.label_width, self._threads)
        if fails:
            raise RuntimeError(f"native decode failed for {fails} records")
        lab = labels[:, 0] if self.label_width == 1 else labels
        return DataBatch([nd_array(data)], [nd_array(lab)], 0, None)


def _resize_short(img, size):
    from PIL import Image

    h, w = img.shape[:2]
    if h < w:
        nh, nw = size, int(w * size / h)
    else:
        nh, nw = int(h * size / w), size
    pil = Image.fromarray(img)
    return np.asarray(pil.resize((nw, nh), Image.BILINEAR))


def _center_crop(img, h, w):
    H, W = img.shape[:2]
    if H < h or W < w:
        img = _pad_to(img, max(h, H), max(w, W))
        H, W = img.shape[:2]
    y0, x0 = (H - h) // 2, (W - w) // 2
    return img[y0:y0 + h, x0:x0 + w]


def _rand_crop(img, h, w, rng=None):
    rng = rng if rng is not None else np.random
    H, W = img.shape[:2]
    if H < h or W < w:
        img = _pad_to(img, max(h, H), max(w, W))
        H, W = img.shape[:2]
    y0 = rng.randint(0, H - h + 1)
    x0 = rng.randint(0, W - w + 1)
    return img[y0:y0 + h, x0:x0 + w]


def _pad_to(img, h, w):
    ph, pw = max(0, h - img.shape[0]), max(0, w - img.shape[1])
    return np.pad(img, ((0, ph), (0, pw), (0, 0)), mode="edge")


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur >= self.size:
            raise StopIteration
        self.cur += 1
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()


class PrefetchingIter(DataIter):
    """Background-thread prefetch wrapper (reference PrefetchingIter /
    PrefetcherIter in src/io/ — double-buffers host batches so device
    compute overlaps decode)."""

    def __init__(self, iters, rename_data=None, rename_label=None, prefetch=2,
                 num_threads=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        assert len(iters) == 1, "single backing iter supported"
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self._prefetch = max(prefetch, num_threads)
        # 2 workers by default: one batch's CPU decode overlaps another's
        # host→device transfer (the tunnel transfer is wait-bound, not
        # CPU-bound, so this wins even on a 1-core host). Safe because the
        # backing iter reserves offsets under a lock (_advance) when it
        # supports split-phase loading.
        self._num_threads = (num_threads
                             if hasattr(self.iter, "_load_batch") else 1)
        self._pool = None
        self._queue = []
        # kick off the first prefetches NOW: the first next() (typically the
        # step right after trainer construction) finds its batch already
        # decoded and in flight to the device instead of paying a cold fetch
        self._ensure_pool()
        while len(self._queue) < self._prefetch:
            self._submit_one()

    @staticmethod
    def _start_transfer(batch):
        """Begin the host→device copy from the worker thread. jax.device_put
        is async — it returns immediately with an in-flight buffer — so the
        consumer's device step overlaps the next batch's decode+transfer."""
        try:
            import jax

            for arr in list(batch.data) + list(batch.label or []):
                if hasattr(arr, "_set_data"):
                    arr._set_data(jax.device_put(arr._data))
        except Exception:
            pass  # never fail a fetch over an optimistic transfer
        return batch

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label

    def reset(self):
        self._drain()
        self.iter.reset()

    def set_partition(self, part_index, num_parts):
        """Recut the backing iterator's shard; in-flight prefetches are
        drained first so no batch from the old cut leaks into the new.
        (Positioning/checkpoint state stays intentionally unimplemented:
        the backing cursor runs ahead of the consumer by up to ``prefetch``
        reserved batches, so a naive snapshot would skip batches on
        resume.)"""
        self._drain()
        self.iter.set_partition(part_index, num_parts)

    def _drain(self):
        for f in self._queue:
            try:
                f.result()
            except StopIteration:
                pass
        self._queue = []

    def _ensure_pool(self):
        if self._pool is None:
            import concurrent.futures as cf

            self._pool = cf.ThreadPoolExecutor(self._num_threads)

    def _submit_one(self):
        """Queue one batch fetch. Offsets (and the augmentation seed) are
        reserved HERE on the consumer thread — submission order IS
        delivery order, so multi-worker prefetch keeps the backing iter's
        (seeded) batch order and can never drop a trailing batch behind an
        earlier StopIteration."""
        import concurrent.futures as cf

        if self._num_threads > 1:
            try:
                reserved = self.iter._advance()
            except StopIteration as e:
                fut = cf.Future()
                fut.set_exception(e)
                self._queue.append(fut)
                return
            self._queue.append(self._pool.submit(
                lambda r: self._start_transfer(self.iter._load_batch(r)),
                reserved))
        else:
            self._queue.append(self._pool.submit(
                lambda: self._start_transfer(self.iter.next())))

    def next(self):
        self._ensure_pool()
        while len(self._queue) < self._prefetch:
            self._submit_one()
        fut = self._queue.pop(0)
        self._submit_one()
        rec = obs.enabled()
        if rec:
            # queue depth = batches already decoded and waiting; a depth
            # pinned at 0 means the consumer is data-bound
            obs.set_gauge("io.prefetch.queue_depth",
                          sum(1 for f in self._queue if f.done()))
            t0 = time.monotonic()
        try:
            batch = fut.result()
        except StopIteration:
            self._drain()
            raise
        if rec:
            # producer stall: how long the step loop blocked because the
            # prefetch workers hadn't finished this batch (≈0 when ahead)
            obs.observe("io.prefetch.stall_seconds", time.monotonic() - t0)
            obs.inc("io.prefetch.batches")
        return batch

    def close(self):
        """Stop the prefetch workers and drop pending batches. Call when
        done timing/training — leftover workers otherwise keep decoding up
        to `prefetch` batches and contend with whatever runs next (this
        polluted round-4 bench sections before it existed)."""
        for f in self._queue:
            f.cancel()
        self._queue = []
        if self._pool is not None:
            self._pool.shutdown(wait=True, cancel_futures=True)
            self._pool = None

    def __del__(self):  # best-effort cleanup
        try:
            self.close()
        except Exception:
            pass
