"""``mx.np`` — the NumPy-compatible frontend.

Reference: ``python/mxnet/numpy/`` (a large re-implementation of numpy
semantics over the op registry — TBV, SURVEY.md §2.3).

Two layers here:
- ``_ops.py`` carries EXPLICIT implementations of the most-used numpy ops
  with mxnet-numpy semantics — ``out=``, ``where=``, float32-default dtype
  promotion, NDArray returns (see its docstring; tests:
  tests/test_numpy_semantics.py);
- anything not explicitly implemented falls back to a jnp delegate,
  unwrapping/wrapping :class:`NDArray` at the boundary.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _onp

from ..ndarray import NDArray
from ..ndarray.ndarray import invoke_fn
from . import linalg, random  # noqa: F401 — mx.np.random / mx.np.linalg
from ._ops import *  # noqa: F401,F403

__all__ = ["ndarray", "array", "zeros", "ones", "empty", "full", "arange",
           "random", "linalg"]

ndarray = NDArray

pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
euler_gamma = _onp.euler_gamma

float32 = _onp.float32
float64 = _onp.float64
float16 = _onp.float16
int8 = _onp.int8
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
bfloat16 = jnp.bfloat16


def _unwrap(x):
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _wrap(x):
    import jax

    if isinstance(x, (jax.Array,)):
        return NDArray(x)
    if isinstance(x, tuple):
        return tuple(_wrap(v) for v in x)
    if isinstance(x, list):
        return [_wrap(v) for v in x]
    return x


def array(object, dtype=None, ctx=None, device=None):
    from ..ndarray import array as nd_array

    return nd_array(object, ctx=ctx or device, dtype=dtype)


def zeros(shape, dtype=None, order="C", ctx=None, device=None):
    from ..ndarray import zeros as nd_zeros

    return nd_zeros(shape, ctx=ctx or device, dtype=dtype or "float32")


def ones(shape, dtype=None, order="C", ctx=None, device=None):
    from ..ndarray import ones as nd_ones

    return nd_ones(shape, ctx=ctx or device, dtype=dtype or "float32")


def full(shape, fill_value, dtype=None, ctx=None, device=None):
    from ..ndarray import full as nd_full

    return nd_full(shape, fill_value, ctx=ctx or device, dtype=dtype or "float32")


def empty(shape, dtype=None, ctx=None, device=None):
    return zeros(shape, dtype=dtype, ctx=ctx, device=device)


def arange(start, stop=None, step=1, dtype=None, ctx=None, device=None):
    from ..ndarray import arange as nd_arange

    return nd_arange(start, stop, step, ctx=ctx or device,
                     dtype=dtype or "float32")


_warned_delegates: set = set()


def _warn_delegate(name):
    """Once-per-op warning: jnp semantics ≠ mxnet-numpy semantics (float64
    promotion, plain-array kwargs handling). Silent wrong-by-design was
    VERDICT r3 weak #5; loud is the contract now. Silence with
    MXNET_NP_SILENT_FALLBACK=1."""
    import os
    import warnings

    if name in _warned_delegates or os.environ.get(
            "MXNET_NP_SILENT_FALLBACK"):
        return
    _warned_delegates.add(name)
    warnings.warn(
        f"mx.np.{name} is not explicitly implemented and falls back to "
        "jax.numpy semantics (dtype promotion, out=/where= handling may "
        "differ from MXNet's numpy). Set MXNET_NP_SILENT_FALLBACK=1 to "
        "silence.", UserWarning, stacklevel=3)


def _make_delegate(name):
    fn = getattr(jnp, name)

    def wrapper(*args, **kwargs):
        _warn_delegate(name)
        nd_args = [a for a in args if isinstance(a, NDArray)]
        if nd_args:
            # route through invoke_fn so autograd records the call
            def pure(*tensor_args, **kw):
                it = iter(tensor_args)
                rebuilt = [next(it) if isinstance(a, NDArray) else _unwrap(a)
                           for a in args]
                return fn(*rebuilt, **{k: _unwrap(v) for k, v in kw.items()})

            return invoke_fn(pure, nd_args, kwargs)
        return _wrap(fn(*[_unwrap(a) for a in args],
                        **{k: _unwrap(v) for k, v in kwargs.items()}))

    wrapper.__name__ = name
    wrapper.__doc__ = fn.__doc__
    return wrapper


def __getattr__(name):
    # explicit ops are bound by the star-import above; only unimplemented
    # names reach this fallback delegate
    if hasattr(jnp, name):
        attr = getattr(jnp, name)
        if callable(attr) and not isinstance(attr, type):
            f = _make_delegate(name)
            globals()[name] = f
            return f
        return attr
    raise AttributeError(f"module 'mxnet_tpu.numpy' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + dir(jnp)))
