"""Explicit mx.np operator implementations with mxnet-numpy semantics.

Reference: ``python/mxnet/numpy/multiarray.py`` + ``src/operator/numpy/*``
(TBV — SURVEY.md §2.2 Numpy row). What "mxnet-numpy semantics" means beyond
raw jnp delegation (the round-2 approach, which got these wrong silently):

- ``out=``: the result lands in the given ndarray (rebinding its buffer —
  reference in-place write) and that same ndarray is returned;
- ``where=`` on binary ufuncs: elements where the mask is False come from
  ``out`` (which numpy requires to be meaningful in that case);
- default float dtype is float32 — integer inputs to mean/std/var/divide
  promote to float32, never float64 (the reference's global
  ``npx.set_np(dtype=...)`` default);
- every result is an :class:`NDArray` (mx.np.ndarray), recorded on the
  autograd tape via invoke_fn.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _onp

from ..ndarray import NDArray
from ..ndarray.ndarray import invoke_fn

__all__: list = []

_EXPLICIT = {}


def _np_op(name):
    def deco(fn):
        _EXPLICIT[name] = fn
        fn.__name__ = name
        globals()[name] = fn  # the ufunc factories don't assign the name
        __all__.append(name)
        return fn
    return deco


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


def _invoke(pure, arrays, out=None):
    """Run ``pure`` over the NDArray inputs (autograd-recorded); honor out=."""
    nds = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
           for a in arrays]
    res = invoke_fn(pure, nds)
    if out is not None:
        if not isinstance(out, NDArray):
            raise TypeError("out= must be an mx.np.ndarray")
        first = res[0] if isinstance(res, (tuple, list)) else res
        out._set_data(first._data.astype(out.dtype))
        return out
    return res


def _binary(name, fn):
    @_np_op(name)
    def op(x1, x2, out=None, where=True, **kwargs):
        if where is True or where is None:
            return _invoke(lambda a, b: fn(a, b), [x1, x2], out)
        if out is None:
            raise ValueError(
                f"np.{name}: where= requires out= (unselected elements are "
                "taken from out, matching numpy)")
        mask = _unwrap(where)
        return _invoke(
            lambda a, b, base: jnp.where(mask, fn(a, b).astype(base.dtype),
                                         base),
            [x1, x2, out], out)
    return op


_binary("add", jnp.add)
_binary("subtract", jnp.subtract)
_binary("multiply", jnp.multiply)
_binary("mod", jnp.mod)
_binary("remainder", jnp.remainder)
_binary("power", jnp.power)
_binary("maximum", jnp.maximum)
_binary("minimum", jnp.minimum)
_binary("hypot", jnp.hypot)
_binary("arctan2", jnp.arctan2)
_binary("copysign", jnp.copysign)


def _to_float(x):
    return (x.astype(jnp.float32)
            if jnp.issubdtype(x.dtype, jnp.integer) or x.dtype == jnp.bool_
            else x)


@_np_op("divide")
def divide(x1, x2, out=None, where=True, **kwargs):
    # int/int division is float32 (mxnet default float), never float64
    if where is True or where is None:
        return _invoke(lambda a, b: jnp.divide(_to_float(a), _to_float(b)),
                       [x1, x2], out)
    if out is None:
        raise ValueError("np.divide: where= requires out=")
    mask = _unwrap(where)
    return _invoke(
        lambda a, b, base: jnp.where(
            mask, jnp.divide(_to_float(a), _to_float(b)).astype(base.dtype),
            base),
        [x1, x2, out], out)


true_divide = divide
_EXPLICIT["true_divide"] = divide
__all__.append("true_divide")


def _unary(name, fn):
    @_np_op(name)
    def op(x, out=None, where=True, **kwargs):
        if where is True or where is None:
            return _invoke(fn, [x], out)
        if out is None:
            raise ValueError(f"np.{name}: where= requires out=")
        mask = _unwrap(where)
        return _invoke(
            lambda a, base: jnp.where(mask, fn(a).astype(base.dtype), base),
            [x, out], out)
    return op


_unary("sqrt", jnp.sqrt)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log2", jnp.log2)
_unary("log10", jnp.log10)
_unary("abs", jnp.abs)
_unary("absolute", jnp.abs)
_unary("sign", jnp.sign)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("negative", jnp.negative)
_unary("reciprocal", lambda x: jnp.reciprocal(_to_float(x)))
_unary("square", jnp.square)
_unary("rint", jnp.rint)
_unary("floor", jnp.floor)
_unary("ceil", jnp.ceil)
_unary("trunc", jnp.trunc)


def _axis_tuple(axis):
    if axis is None or isinstance(axis, int):
        return axis
    return tuple(axis)


def _reduction(name, fn, float_result=False):
    @_np_op(name)
    def op(a, axis=None, dtype=None, out=None, keepdims=False, **kwargs):
        def pure(x):
            xx = _to_float(x) if float_result and dtype is None else x
            if dtype is not None:
                xx = x.astype(dtype)
            return fn(xx, axis=_axis_tuple(axis), keepdims=keepdims)
        return _invoke(pure, [a], out)
    return op


_reduction("sum", jnp.sum)
_reduction("prod", jnp.prod)
_reduction("mean", jnp.mean, float_result=True)
_reduction("max", jnp.max)
_reduction("min", jnp.min)
_reduction("amax", jnp.max)
_reduction("amin", jnp.min)


@_np_op("std")
def std(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False, **kw):
    def pure(x):
        xx = _to_float(x) if dtype is None else x.astype(dtype)
        return jnp.std(xx, axis=_axis_tuple(axis), ddof=ddof,
                       keepdims=keepdims)
    return _invoke(pure, [a], out)


@_np_op("var")
def var(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False, **kw):
    def pure(x):
        xx = _to_float(x) if dtype is None else x.astype(dtype)
        return jnp.var(xx, axis=_axis_tuple(axis), ddof=ddof,
                       keepdims=keepdims)
    return _invoke(pure, [a], out)


@_np_op("argmax")
def argmax(a, axis=None, out=None, **kw):
    # reference returns int64; with x64 disabled int32 is the TPU-native max
    return _invoke(lambda x: jnp.argmax(x, axis=axis).astype(jnp.int32),
                   [a], out)


@_np_op("argmin")
def argmin(a, axis=None, out=None, **kw):
    return _invoke(lambda x: jnp.argmin(x, axis=axis).astype(jnp.int32),
                   [a], out)


@_np_op("clip")
def clip(a, a_min=None, a_max=None, out=None, **kw):
    return _invoke(lambda x: jnp.clip(x, a_min, a_max), [a], out)


@_np_op("dot")
def dot(a, b, out=None):
    return _invoke(lambda x, y: jnp.dot(x, y), [a, b], out)


@_np_op("matmul")
def matmul(a, b, out=None, **kw):
    return _invoke(lambda x, y: jnp.matmul(x, y), [a, b], out)


@_np_op("tensordot")
def tensordot(a, b, axes=2):
    ax = axes
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(x) if isinstance(x, (list, tuple)) else x for x in ax)
    return _invoke(lambda x, y: jnp.tensordot(x, y, axes=ax), [a, b])


@_np_op("concatenate")
def concatenate(seq, axis=0, out=None):
    arrays = list(seq)
    return _invoke(lambda *ts: jnp.concatenate(ts, axis=axis), arrays, out)


@_np_op("stack")
def stack(arrays, axis=0, out=None):
    arrays = list(arrays)
    return _invoke(lambda *ts: jnp.stack(ts, axis=axis), arrays, out)


@_np_op("split")
def split(ary, indices_or_sections, axis=0):
    ios = indices_or_sections
    if isinstance(ios, (list, tuple)):
        ios = tuple(int(i) for i in ios)
    n_out = (len(ios) + 1 if isinstance(ios, tuple) else int(ios))
    outs = _invoke(lambda x: tuple(jnp.split(x, ios, axis=axis)), [ary])
    return list(outs) if isinstance(outs, (tuple, list)) else [outs]


@_np_op("where")
def where(condition, x=None, y=None):
    if x is None and y is None:
        cond = _unwrap(condition)
        return tuple(NDArray(i.astype(jnp.int32)) for i in jnp.nonzero(cond))
    return _invoke(lambda c, a, b: jnp.where(c, a, b), [condition, x, y])


@_np_op("reshape")
def reshape(a, newshape, order="C"):
    return _invoke(lambda x: jnp.reshape(x, newshape), [a])


@_np_op("transpose")
def transpose(a, axes=None):
    return _invoke(lambda x: jnp.transpose(x, axes), [a])


@_np_op("swapaxes")
def swapaxes(a, axis1, axis2):
    return _invoke(lambda x: jnp.swapaxes(x, axis1, axis2), [a])


@_np_op("expand_dims")
def expand_dims(a, axis):
    return _invoke(lambda x: jnp.expand_dims(x, axis), [a])


@_np_op("squeeze")
def squeeze(a, axis=None):
    return _invoke(lambda x: jnp.squeeze(x, axis), [a])


@_np_op("broadcast_to")
def broadcast_to(array, shape):
    return _invoke(lambda x: jnp.broadcast_to(x, shape), [array])


@_np_op("repeat")
def repeat(a, repeats, axis=None):
    return _invoke(lambda x: jnp.repeat(x, repeats, axis=axis), [a])


@_np_op("tile")
def tile(a, reps):
    return _invoke(lambda x: jnp.tile(x, reps), [a])


@_np_op("cumsum")
def cumsum(a, axis=None, dtype=None, out=None):
    def pure(x):
        r = jnp.cumsum(x.reshape(-1) if axis is None else x,
                       axis=0 if axis is None else axis)
        return r.astype(dtype) if dtype else r
    return _invoke(pure, [a], out)


@_np_op("copy")
def copy(a):
    return _invoke(lambda x: x + jnp.zeros((), x.dtype), [a])


@_np_op("linspace")
def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None, device=None):
    out = jnp.linspace(start, stop, int(num), endpoint=endpoint,
                       retstep=retstep, dtype=dtype or jnp.float32, axis=axis)
    if retstep:
        return NDArray(out[0]), float(out[1])
    return NDArray(out)
