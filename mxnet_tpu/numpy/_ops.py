"""Explicit mx.np operator implementations with mxnet-numpy semantics.

Reference: ``python/mxnet/numpy/multiarray.py`` + ``src/operator/numpy/*``
(TBV — SURVEY.md §2.2 Numpy row). What "mxnet-numpy semantics" means beyond
raw jnp delegation (the round-2 approach, which got these wrong silently):

- ``out=``: the result lands in the given ndarray (rebinding its buffer —
  reference in-place write) and that same ndarray is returned;
- ``where=`` on binary ufuncs: elements where the mask is False come from
  ``out`` (which numpy requires to be meaningful in that case);
- default float dtype is float32 — integer inputs to mean/std/var/divide
  promote to float32, never float64 (the reference's global
  ``npx.set_np(dtype=...)`` default);
- every result is an :class:`NDArray` (mx.np.ndarray), recorded on the
  autograd tape via invoke_fn.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _onp

from ..ndarray import NDArray
from ..ndarray.ndarray import invoke_fn

__all__: list = []

_EXPLICIT = {}


def _np_op(name):
    def deco(fn):
        _EXPLICIT[name] = fn
        fn.__name__ = name
        globals()[name] = fn  # the ufunc factories don't assign the name
        __all__.append(name)
        return fn
    return deco


def _unwrap(x):
    return x._data if isinstance(x, NDArray) else x


def _invoke(pure, arrays, out=None):
    """Run ``pure`` over the NDArray inputs (autograd-recorded); honor out=."""
    nds = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
           for a in arrays]
    res = invoke_fn(pure, nds)
    if out is not None:
        if not isinstance(out, NDArray):
            raise TypeError("out= must be an mx.np.ndarray")
        first = res[0] if isinstance(res, (tuple, list)) else res
        out._set_data(first._data.astype(out.dtype))
        return out
    return res


def _binary(name, fn):
    @_np_op(name)
    def op(x1, x2, out=None, where=True, **kwargs):
        if where is True or where is None:
            return _invoke(lambda a, b: fn(a, b), [x1, x2], out)
        if out is None:
            raise ValueError(
                f"np.{name}: where= requires out= (unselected elements are "
                "taken from out, matching numpy)")
        mask = _unwrap(where)
        return _invoke(
            lambda a, b, base: jnp.where(mask, fn(a, b).astype(base.dtype),
                                         base),
            [x1, x2, out], out)
    return op


_binary("add", jnp.add)
_binary("subtract", jnp.subtract)
_binary("multiply", jnp.multiply)
_binary("mod", jnp.mod)
_binary("remainder", jnp.remainder)
_binary("power", jnp.power)
_binary("maximum", jnp.maximum)
_binary("minimum", jnp.minimum)
_binary("hypot", jnp.hypot)
_binary("arctan2", jnp.arctan2)
_binary("copysign", jnp.copysign)


def _to_float(x):
    return (x.astype(jnp.float32)
            if jnp.issubdtype(x.dtype, jnp.integer) or x.dtype == jnp.bool_
            else x)


@_np_op("divide")
def divide(x1, x2, out=None, where=True, **kwargs):
    # int/int division is float32 (mxnet default float), never float64
    if where is True or where is None:
        return _invoke(lambda a, b: jnp.divide(_to_float(a), _to_float(b)),
                       [x1, x2], out)
    if out is None:
        raise ValueError("np.divide: where= requires out=")
    mask = _unwrap(where)
    return _invoke(
        lambda a, b, base: jnp.where(
            mask, jnp.divide(_to_float(a), _to_float(b)).astype(base.dtype),
            base),
        [x1, x2, out], out)


true_divide = divide
_EXPLICIT["true_divide"] = divide
__all__.append("true_divide")


def _unary(name, fn):
    @_np_op(name)
    def op(x, out=None, where=True, **kwargs):
        if where is True or where is None:
            return _invoke(fn, [x], out)
        if out is None:
            raise ValueError(f"np.{name}: where= requires out=")
        mask = _unwrap(where)
        return _invoke(
            lambda a, base: jnp.where(mask, fn(a).astype(base.dtype), base),
            [x, out], out)
    return op


_unary("sqrt", jnp.sqrt)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log2", jnp.log2)
_unary("log10", jnp.log10)
_unary("abs", jnp.abs)
_unary("absolute", jnp.abs)
_unary("sign", jnp.sign)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("negative", jnp.negative)
_unary("reciprocal", lambda x: jnp.reciprocal(_to_float(x)))
_unary("square", jnp.square)
_unary("rint", jnp.rint)
_unary("floor", jnp.floor)
_unary("ceil", jnp.ceil)
_unary("trunc", jnp.trunc)


def _axis_tuple(axis):
    if axis is None or isinstance(axis, int):
        return axis
    return tuple(axis)


def _reduction(name, fn, float_result=False):
    @_np_op(name)
    def op(a, axis=None, dtype=None, out=None, keepdims=False, **kwargs):
        def pure(x):
            xx = _to_float(x) if float_result and dtype is None else x
            if dtype is not None:
                xx = x.astype(dtype)
            return fn(xx, axis=_axis_tuple(axis), keepdims=keepdims)
        return _invoke(pure, [a], out)
    return op


_reduction("sum", jnp.sum)
_reduction("prod", jnp.prod)
_reduction("mean", jnp.mean, float_result=True)
_reduction("max", jnp.max)
_reduction("min", jnp.min)
_reduction("amax", jnp.max)
_reduction("amin", jnp.min)


@_np_op("std")
def std(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False, **kw):
    def pure(x):
        xx = _to_float(x) if dtype is None else x.astype(dtype)
        return jnp.std(xx, axis=_axis_tuple(axis), ddof=ddof,
                       keepdims=keepdims)
    return _invoke(pure, [a], out)


@_np_op("var")
def var(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False, **kw):
    def pure(x):
        xx = _to_float(x) if dtype is None else x.astype(dtype)
        return jnp.var(xx, axis=_axis_tuple(axis), ddof=ddof,
                       keepdims=keepdims)
    return _invoke(pure, [a], out)


@_np_op("argmax")
def argmax(a, axis=None, out=None, **kw):
    # reference returns int64; with x64 disabled int32 is the TPU-native max
    return _invoke(lambda x: jnp.argmax(x, axis=axis).astype(jnp.int32),
                   [a], out)


@_np_op("argmin")
def argmin(a, axis=None, out=None, **kw):
    return _invoke(lambda x: jnp.argmin(x, axis=axis).astype(jnp.int32),
                   [a], out)


@_np_op("clip")
def clip(a, a_min=None, a_max=None, out=None, **kw):
    return _invoke(lambda x: jnp.clip(x, a_min, a_max), [a], out)


@_np_op("dot")
def dot(a, b, out=None):
    return _invoke(lambda x, y: jnp.dot(x, y), [a, b], out)


@_np_op("matmul")
def matmul(a, b, out=None, **kw):
    return _invoke(lambda x, y: jnp.matmul(x, y), [a, b], out)


@_np_op("tensordot")
def tensordot(a, b, axes=2):
    ax = axes
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(x) if isinstance(x, (list, tuple)) else x for x in ax)
    return _invoke(lambda x, y: jnp.tensordot(x, y, axes=ax), [a, b])


@_np_op("concatenate")
def concatenate(seq, axis=0, out=None):
    arrays = list(seq)
    return _invoke(lambda *ts: jnp.concatenate(ts, axis=axis), arrays, out)


@_np_op("stack")
def stack(arrays, axis=0, out=None):
    arrays = list(arrays)
    return _invoke(lambda *ts: jnp.stack(ts, axis=axis), arrays, out)


@_np_op("split")
def split(ary, indices_or_sections, axis=0):
    ios = indices_or_sections
    if isinstance(ios, (list, tuple)):
        ios = tuple(int(i) for i in ios)
    n_out = (len(ios) + 1 if isinstance(ios, tuple) else int(ios))
    outs = _invoke(lambda x: tuple(jnp.split(x, ios, axis=axis)), [ary])
    return list(outs) if isinstance(outs, (tuple, list)) else [outs]


@_np_op("where")
def where(condition, x=None, y=None):
    if x is None and y is None:
        cond = _unwrap(condition)
        return tuple(NDArray(i.astype(jnp.int32)) for i in jnp.nonzero(cond))
    return _invoke(lambda c, a, b: jnp.where(c, a, b), [condition, x, y])


@_np_op("reshape")
def reshape(a, newshape, order="C"):
    return _invoke(lambda x: jnp.reshape(x, newshape), [a])


@_np_op("transpose")
def transpose(a, axes=None):
    return _invoke(lambda x: jnp.transpose(x, axes), [a])


@_np_op("swapaxes")
def swapaxes(a, axis1, axis2):
    return _invoke(lambda x: jnp.swapaxes(x, axis1, axis2), [a])


@_np_op("expand_dims")
def expand_dims(a, axis):
    return _invoke(lambda x: jnp.expand_dims(x, axis), [a])


@_np_op("squeeze")
def squeeze(a, axis=None):
    return _invoke(lambda x: jnp.squeeze(x, axis), [a])


@_np_op("broadcast_to")
def broadcast_to(array, shape):
    return _invoke(lambda x: jnp.broadcast_to(x, shape), [array])


@_np_op("repeat")
def repeat(a, repeats, axis=None):
    return _invoke(lambda x: jnp.repeat(x, repeats, axis=axis), [a])


@_np_op("tile")
def tile(a, reps):
    return _invoke(lambda x: jnp.tile(x, reps), [a])


@_np_op("cumsum")
def cumsum(a, axis=None, dtype=None, out=None):
    def pure(x):
        r = jnp.cumsum(x.reshape(-1) if axis is None else x,
                       axis=0 if axis is None else axis)
        return r.astype(dtype) if dtype else r
    return _invoke(pure, [a], out)


@_np_op("copy")
def copy(a):
    return _invoke(lambda x: x + jnp.zeros((), x.dtype), [a])


@_np_op("linspace")
def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None, device=None):
    out = jnp.linspace(start, stop, int(num), endpoint=endpoint,
                       retstep=retstep, dtype=dtype or jnp.float32, axis=axis)
    if retstep:
        return NDArray(out[0]), float(out[1])
    return NDArray(out)


# ---------------------------------------------------------------------------
# Round-4 expansion (VERDICT r3 item 7): the next tier of most-used numpy
# ops, explicit instead of silently delegated. Same semantics contract as
# above: out=/where= honored, float32 (never float64) default promotion,
# int32 index dtypes, NDArray returns on the tape.
# ---------------------------------------------------------------------------

# comparisons (bool results; where= via the ufunc factory)
_binary("equal", jnp.equal)
_binary("not_equal", jnp.not_equal)
_binary("less", jnp.less)
_binary("less_equal", jnp.less_equal)
_binary("greater", jnp.greater)
_binary("greater_equal", jnp.greater_equal)

# logical / bitwise
_binary("logical_and", jnp.logical_and)
_binary("logical_or", jnp.logical_or)
_binary("logical_xor", jnp.logical_xor)
_unary("logical_not", jnp.logical_not)
_binary("bitwise_and", jnp.bitwise_and)
_binary("bitwise_or", jnp.bitwise_or)
_binary("bitwise_xor", jnp.bitwise_xor)
_unary("bitwise_not", jnp.bitwise_not)
_unary("invert", jnp.invert)
_binary("left_shift", jnp.left_shift)
_binary("right_shift", jnp.right_shift)

# more binary ufuncs
_binary("floor_divide", jnp.floor_divide)     # int//int stays int
_binary("fmod", jnp.fmod)
_binary("gcd", jnp.gcd)
_binary("lcm", jnp.lcm)
_binary("heaviside", jnp.heaviside)
_binary("logaddexp", jnp.logaddexp)
_binary("fmax", jnp.fmax)
_binary("fmin", jnp.fmin)

# more unary ufuncs
_unary("expm1", jnp.expm1)
_unary("log1p", jnp.log1p)
_unary("exp2", jnp.exp2)
_unary("cbrt", jnp.cbrt)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("deg2rad", jnp.deg2rad)
_unary("rad2deg", jnp.rad2deg)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("isnan", jnp.isnan)
_unary("isinf", jnp.isinf)
_unary("isfinite", jnp.isfinite)
_unary("isposinf", jnp.isposinf)
_unary("isneginf", jnp.isneginf)
_unary("fix", jnp.trunc)  # jnp.fix is deprecated; trunc is identical on reals
_unary("positive", jnp.positive)
_unary("conj", jnp.conj)
_unary("conjugate", jnp.conjugate)


@_np_op("round")
def round(x, decimals=0, out=None, **kw):  # noqa: A001 - numpy name
    return _invoke(lambda a: jnp.round(a, decimals=decimals), [x], out)


_EXPLICIT["around"] = round
_EXPLICIT["round_"] = round
globals()["around"] = round
globals()["round_"] = round
__all__ += ["around", "round_"]


@_np_op("nan_to_num")
def nan_to_num(x, copy=True, nan=0.0, posinf=None, neginf=None):
    return _invoke(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                            neginf=neginf), [x])

# reductions
_reduction("all", jnp.all)
_reduction("any", jnp.any)
_reduction("nansum", jnp.nansum)
_reduction("nanmax", jnp.nanmax)
_reduction("nanmin", jnp.nanmin)
_reduction("nanmean", jnp.nanmean, float_result=True)
_reduction("nanprod", jnp.nanprod)


@_np_op("ptp")
def ptp(a, axis=None, out=None, keepdims=False):
    return _invoke(lambda x: jnp.ptp(x, axis=_axis_tuple(axis),
                                     keepdims=keepdims), [a], out)


@_np_op("median")
def median(a, axis=None, out=None, keepdims=False, **kw):
    return _invoke(lambda x: jnp.median(_to_float(x), axis=_axis_tuple(axis),
                                        keepdims=keepdims), [a], out)


@_np_op("quantile")
def quantile(a, q, axis=None, out=None, keepdims=False, **kw):
    return _invoke(lambda x: jnp.quantile(
        _to_float(x), jnp.asarray(_unwrap(q), jnp.float32),
        axis=_axis_tuple(axis), keepdims=keepdims), [a], out)


@_np_op("percentile")
def percentile(a, q, axis=None, out=None, keepdims=False, **kw):
    return _invoke(lambda x: jnp.percentile(
        _to_float(x), jnp.asarray(_unwrap(q), jnp.float32),
        axis=_axis_tuple(axis), keepdims=keepdims), [a], out)


@_np_op("average")
def average(a, axis=None, weights=None, returned=False):
    if weights is None:
        res = _invoke(lambda x: jnp.mean(_to_float(x),
                                         axis=_axis_tuple(axis)), [a])
        if returned:
            cnt = _onp.prod([_unwrap(a).shape[ax] for ax in (
                range(_unwrap(a).ndim) if axis is None
                else ([axis] if isinstance(axis, int) else axis))])
            return res, full_like_scalar(res, float(cnt))
        return res
    res = _invoke(
        lambda x, w: jnp.average(_to_float(x), axis=_axis_tuple(axis),
                                 weights=_to_float(w)), [a, weights])
    if returned:
        wsum = _invoke(lambda w: jnp.sum(_to_float(w),
                                         axis=_axis_tuple(axis)), [weights])
        return res, wsum
    return res


def full_like_scalar(like, value):
    return NDArray(jnp.full(_unwrap(like).shape, value, jnp.float32))


@_np_op("cumprod")
def cumprod(a, axis=None, dtype=None, out=None):
    def pure(x):
        r = jnp.cumprod(x.reshape(-1) if axis is None else x,
                        axis=0 if axis is None else axis)
        return r.astype(dtype) if dtype else r
    return _invoke(pure, [a], out)


# sorting / searching (index dtypes int32 — TPU-native, x64 disabled)
@_np_op("sort")
def sort(a, axis=-1, kind=None, order=None):
    return _invoke(lambda x: jnp.sort(x, axis=axis), [a])


@_np_op("argsort")
def argsort(a, axis=-1, kind=None, order=None):
    return _invoke(lambda x: jnp.argsort(x, axis=axis).astype(jnp.int32), [a])


@_np_op("searchsorted")
def searchsorted(a, v, side="left", sorter=None):
    arrays = [a, v] if sorter is None else [a, v, sorter]
    return _invoke(lambda *ts: jnp.searchsorted(
        ts[0], ts[1], side=side,
        sorter=ts[2] if len(ts) > 2 else None).astype(jnp.int32), arrays)


@_np_op("nonzero")
def nonzero(a):
    cond = _unwrap(a)
    return tuple(NDArray(i.astype(jnp.int32)) for i in jnp.nonzero(cond))


@_np_op("count_nonzero")
def count_nonzero(a, axis=None):
    return _invoke(lambda x: jnp.count_nonzero(x, axis=_axis_tuple(axis))
                   .astype(jnp.int32), [a])


@_np_op("unique")
def unique(ar, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    res = jnp.unique(_unwrap(ar), return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        out = [NDArray(res[0])]
        for extra in res[1:]:
            out.append(NDArray(extra.astype(jnp.int32)))
        return tuple(out)
    return NDArray(res)


@_np_op("bincount")
def bincount(x, weights=None, minlength=0):
    xs = _unwrap(x)
    if weights is None:
        return NDArray(jnp.bincount(xs, minlength=minlength)
                       .astype(jnp.int32))
    return NDArray(jnp.bincount(xs, weights=_unwrap(weights),
                                minlength=minlength))


# shape / manipulation
@_np_op("ravel")
def ravel(a, order="C"):
    return _invoke(lambda x: jnp.ravel(x), [a])


@_np_op("flip")
def flip(m, axis=None):
    return _invoke(lambda x: jnp.flip(x, axis=_axis_tuple(axis)), [m])


@_np_op("flipud")
def flipud(m):
    return _invoke(jnp.flipud, [m])


@_np_op("fliplr")
def fliplr(m):
    return _invoke(jnp.fliplr, [m])


@_np_op("roll")
def roll(a, shift, axis=None):
    return _invoke(lambda x: jnp.roll(x, shift, axis=_axis_tuple(axis)), [a])


@_np_op("rot90")
def rot90(m, k=1, axes=(0, 1)):
    return _invoke(lambda x: jnp.rot90(x, k=k, axes=tuple(axes)), [m])


@_np_op("moveaxis")
def moveaxis(a, source, destination):
    return _invoke(lambda x: jnp.moveaxis(x, source, destination), [a])


@_np_op("vstack")
def vstack(tup):
    return _invoke(lambda *ts: jnp.vstack(ts), list(tup))


@_np_op("hstack")
def hstack(tup):
    return _invoke(lambda *ts: jnp.hstack(ts), list(tup))


@_np_op("dstack")
def dstack(tup):
    return _invoke(lambda *ts: jnp.dstack(ts), list(tup))


@_np_op("column_stack")
def column_stack(tup):
    return _invoke(lambda *ts: jnp.column_stack(ts), list(tup))


@_np_op("array_split")
def array_split(ary, indices_or_sections, axis=0):
    ios = indices_or_sections
    if isinstance(ios, (list, tuple)):
        ios = tuple(int(i) for i in ios)
    outs = _invoke(lambda x: tuple(jnp.array_split(x, ios, axis=axis)), [ary])
    return list(outs) if isinstance(outs, (tuple, list)) else [outs]


@_np_op("atleast_1d")
def atleast_1d(*arys):
    res = [_invoke(jnp.atleast_1d, [a]) for a in arys]
    return res[0] if len(res) == 1 else res


@_np_op("atleast_2d")
def atleast_2d(*arys):
    res = [_invoke(jnp.atleast_2d, [a]) for a in arys]
    return res[0] if len(res) == 1 else res


@_np_op("atleast_3d")
def atleast_3d(*arys):
    res = [_invoke(jnp.atleast_3d, [a]) for a in arys]
    return res[0] if len(res) == 1 else res


@_np_op("pad")
def pad(array, pad_width, mode="constant", **kwargs):
    return _invoke(lambda x: jnp.pad(x, pad_width, mode=mode, **kwargs),
                   [array])


@_np_op("take")
def take(a, indices, axis=None, mode="raise", out=None):
    # device arrays can't raise on bad indices; 'raise' behaves as 'clip'
    # (documented deviation, matches mxnet-numpy's default on GPU)
    jmode = "clip" if mode == "raise" else mode
    return _invoke(
        lambda x, idx: jnp.take(x, idx.astype(jnp.int32), axis=axis,
                                mode=jmode), [a, indices], out)


@_np_op("take_along_axis")
def take_along_axis(arr, indices, axis):
    return _invoke(lambda x, idx: jnp.take_along_axis(
        x, idx.astype(jnp.int32), axis=axis), [arr, indices])


@_np_op("meshgrid")
def meshgrid(*xi, indexing="xy", **kw):
    outs = jnp.meshgrid(*[_unwrap(x) for x in xi], indexing=indexing)
    return [NDArray(o) for o in outs]


@_np_op("diff")
def diff(a, n=1, axis=-1):
    return _invoke(lambda x: jnp.diff(x, n=n, axis=axis), [a])


@_np_op("ediff1d")
def ediff1d(ary, to_end=None, to_begin=None):
    return _invoke(lambda x: jnp.ediff1d(
        x, to_end=None if to_end is None else _unwrap(to_end),
        to_begin=None if to_begin is None else _unwrap(to_begin)), [ary])


@_np_op("interp")
def interp(x, xp, fp, left=None, right=None, period=None):
    return _invoke(lambda a, b, c: jnp.interp(
        _to_float(a), _to_float(b), _to_float(c), left=left, right=right,
        period=period), [x, xp, fp])


# linear-algebra-adjacent
@_np_op("outer")
def outer(a, b, out=None):
    return _invoke(lambda x, y: jnp.outer(x, y), [a, b], out)


@_np_op("inner")
def inner(a, b):
    return _invoke(lambda x, y: jnp.inner(x, y), [a, b])


@_np_op("vdot")
def vdot(a, b):
    return _invoke(lambda x, y: jnp.vdot(x, y), [a, b])


@_np_op("kron")
def kron(a, b):
    return _invoke(lambda x, y: jnp.kron(x, y), [a, b])


@_np_op("cross")
def cross(a, b, axisa=-1, axisb=-1, axisc=-1, axis=None):
    return _invoke(lambda x, y: jnp.cross(x, y, axisa=axisa, axisb=axisb,
                                          axisc=axisc, axis=axis), [a, b])


@_np_op("trace")
def trace(a, offset=0, axis1=0, axis2=1, dtype=None, out=None):
    def pure(x):
        r = jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)
        return r.astype(dtype) if dtype else r
    return _invoke(pure, [a], out)


@_np_op("diag")
def diag(v, k=0):
    return _invoke(lambda x: jnp.diag(x, k=k), [v])


@_np_op("diagonal")
def diagonal(a, offset=0, axis1=0, axis2=1):
    return _invoke(lambda x: jnp.diagonal(x, offset=offset, axis1=axis1,
                                          axis2=axis2), [a])


@_np_op("tril")
def tril(m, k=0):
    return _invoke(lambda x: jnp.tril(x, k=k), [m])


@_np_op("triu")
def triu(m, k=0):
    return _invoke(lambda x: jnp.triu(x, k=k), [m])


@_np_op("einsum")
def einsum(subscripts, *operands, out=None, **kwargs):
    return _invoke(lambda *ts: jnp.einsum(subscripts, *ts), list(operands),
                   out)


@_np_op("maximum_reduce")  # internal helper kept explicit for npx users
def maximum_reduce(a, axis=None, keepdims=False):
    return _invoke(lambda x: jnp.max(x, axis=_axis_tuple(axis),
                                     keepdims=keepdims), [a])


# creation (float32 default, never float64)
@_np_op("eye")
def eye(N, M=None, k=0, dtype=None, ctx=None, device=None):
    return NDArray(jnp.eye(int(N), None if M is None else int(M), k=k,
                           dtype=dtype or jnp.float32))


@_np_op("identity")
def identity(n, dtype=None, ctx=None, device=None):
    return NDArray(jnp.identity(int(n), dtype=dtype or jnp.float32))


@_np_op("logspace")
def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, ctx=None, device=None):
    return NDArray(jnp.logspace(start, stop, int(num), endpoint=endpoint,
                                base=base, dtype=dtype or jnp.float32,
                                axis=axis))


@_np_op("tri")
def tri(N, M=None, k=0, dtype=None, ctx=None, device=None):
    return NDArray(jnp.tri(int(N), None if M is None else int(M), k=k,
                           dtype=dtype or jnp.float32))


@_np_op("zeros_like")
def zeros_like(a, dtype=None, order="C", ctx=None, device=None):
    return _invoke(lambda x: jnp.zeros_like(x, dtype=dtype), [a])


@_np_op("ones_like")
def ones_like(a, dtype=None, order="C", ctx=None, device=None):
    return _invoke(lambda x: jnp.ones_like(x, dtype=dtype), [a])


@_np_op("full_like")
def full_like(a, fill_value, dtype=None, order="C", ctx=None, device=None):
    return _invoke(lambda x: jnp.full_like(x, fill_value, dtype=dtype), [a])


@_np_op("isclose")
def isclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return _invoke(lambda x, y: jnp.isclose(x, y, rtol=rtol, atol=atol,
                                            equal_nan=equal_nan), [a, b])


@_np_op("allclose")
def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return bool(jnp.allclose(_unwrap(a), _unwrap(b), rtol=rtol, atol=atol,
                             equal_nan=equal_nan))


@_np_op("array_equal")
def array_equal(a1, a2, equal_nan=False):
    return bool(jnp.array_equal(_unwrap(a1), _unwrap(a2),
                                equal_nan=equal_nan))


# --------------------------------------------------------------------------
# Round-5 explicit promotions (VERDICT r4 item 4: shrink the delegate tail).
# `_tape_op` binds a jnp function whose numpy semantics already coincide
# with mxnet-numpy under this build (32-bit default dtypes: jax_enable_x64
# is off, so the float64-promotion divergence the delegate warns about
# cannot occur in these), recording array inputs on the autograd tape.
# Ops whose mxnet semantics differ from raw jnp get dedicated defs below.
# --------------------------------------------------------------------------

def _unwrap_deep(x):
    """Recursive unwrap: ops like select/row_stack take LISTS of arrays."""
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap_deep(v) for v in x)
    return x


def _tape_op(name, fn):
    @_np_op(name)
    def op(*args, **kwargs):
        arr_idx = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
        kw = {k: _unwrap_deep(v) for k, v in kwargs.items()}
        if not arr_idx:
            res = fn(*[_unwrap_deep(a) for a in args], **kw)
            return _wrap_out(res)

        idx_set = set(arr_idx)

        def pure(*tensors):
            it = iter(tensors)
            rebuilt = [next(it) if i in idx_set else _unwrap_deep(args[i])
                       for i in range(len(args))]
            return fn(*rebuilt, **kw)

        return _invoke(pure, [args[i] for i in arr_idx])
    return op


def _wrap_out(res):
    if isinstance(res, (tuple, list)):
        return type(res)(_wrap_out(r) for r in res)
    if isinstance(res, jax.Array):
        return NDArray(res)
    return res


for _nm, _fn in [
    # elementwise / unary
    ("signbit", jnp.signbit), ("real", jnp.real), ("imag", jnp.imag),
    ("angle", jnp.angle), ("sinc", jnp.sinc), ("i0", jnp.i0),
    ("fabs", jnp.fabs), ("flatnonzero", jnp.flatnonzero),
    ("nextafter", jnp.nextafter), ("ldexp", jnp.ldexp),
    ("frexp", jnp.frexp), ("logaddexp2", jnp.logaddexp2),
    ("divmod", jnp.divmod), ("nanargmax", jnp.nanargmax),
    ("nanargmin", jnp.nanargmin), ("nancumsum", jnp.nancumsum),
    ("nancumprod", jnp.nancumprod),
    # logic / sets / search
    ("isin", jnp.isin), ("digitize", jnp.digitize),
    # manipulation
    ("broadcast_arrays", jnp.broadcast_arrays),
    ("row_stack", jnp.vstack), ("vander", jnp.vander),
    ("delete", jnp.delete), ("append", jnp.append),
    ("resize", jnp.resize), ("compress", jnp.compress),
    ("extract", jnp.extract), ("unwrap", jnp.unwrap),
    ("select", jnp.select), ("trim_zeros", jnp.trim_zeros),
    # math over arrays
    ("convolve", jnp.convolve), ("correlate", jnp.correlate),
    ("polyval", jnp.polyval), ("gradient", jnp.gradient),
    ("histogram", jnp.histogram),
    # index helpers
    ("tril_indices", jnp.tril_indices), ("triu_indices", jnp.triu_indices),
    ("diag_indices", jnp.diag_indices), ("indices", jnp.indices),
    ("ix_", jnp.ix_), ("unravel_index", jnp.unravel_index),
    ("ravel_multi_index", jnp.ravel_multi_index),
]:
    _tape_op(_nm, _fn)


@_np_op("insert")
def insert(arr, obj, values, axis=None):
    return _invoke(lambda a, v: jnp.insert(a, _unwrap(obj), v, axis=axis),
                   [arr, values])


@_np_op("float_power")
def float_power(x1, x2, out=None, **kw):
    # numpy promises float64; the mxnet default float is float32
    return _invoke(lambda a, b: jnp.power(_to_float(a), _to_float(b)),
                   [x1, x2], out)


@_np_op("trapz")
def trapz(y, x=None, dx=1.0, axis=-1):
    arrays = [y] if x is None else [y, x]

    def pure(yy, *maybe_x):
        return jnp.trapezoid(_to_float(yy),
                             _to_float(maybe_x[0]) if maybe_x else None,
                             dx=dx, axis=axis)
    return _invoke(pure, arrays)


@_np_op("nanstd")
def nanstd(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False):
    # int inputs promote to float32 (mxnet default float); float inputs
    # keep their dtype, matching std()/var() above
    def pure(x):
        r = jnp.nanstd(_to_float(x), axis=_axis_tuple(axis), ddof=ddof,
                       keepdims=keepdims)
        return r.astype(dtype) if dtype is not None else r
    return _invoke(pure, [a], out)


@_np_op("nanvar")
def nanvar(a, axis=None, dtype=None, out=None, ddof=0, keepdims=False):
    def pure(x):
        r = jnp.nanvar(_to_float(x), axis=_axis_tuple(axis), ddof=ddof,
                       keepdims=keepdims)
        return r.astype(dtype) if dtype is not None else r
    return _invoke(pure, [a], out)


@_np_op("geomspace")
def geomspace(start, stop, num=50, endpoint=True, dtype=None, axis=0,
              ctx=None, device=None):
    out = jnp.geomspace(_unwrap(start), _unwrap(stop), num=num,
                        endpoint=endpoint, axis=axis)
    return NDArray(out.astype(dtype or jnp.float32))


@_np_op("asarray")
def asarray(a, dtype=None, ctx=None, device=None):
    if isinstance(a, NDArray) and dtype is None:
        return a
    from ..ndarray import array as nd_array

    return nd_array(a, ctx=ctx or device, dtype=dtype)


@_np_op("ascontiguousarray")
def ascontiguousarray(a, dtype=None):
    return asarray(a, dtype=dtype)  # PJRT buffers are always contiguous


@_np_op("empty_like")
def empty_like(a, dtype=None, order="C", ctx=None, device=None):
    return _invoke(lambda x: jnp.zeros_like(x, dtype=dtype), [a])


@_np_op("ndim")
def ndim(a):
    return len(a.shape) if hasattr(a, "shape") else _onp.ndim(a)


@_np_op("shape")
def shape(a):
    return tuple(a.shape) if hasattr(a, "shape") else _onp.shape(a)


@_np_op("size")
def size(a, axis=None):
    if not hasattr(a, "shape"):
        return _onp.size(a, axis)
    if axis is None:
        n = 1
        for d in a.shape:
            n *= d
        return n
    return a.shape[axis]


@_np_op("put")
def put(a, ind, v, mode="clip"):
    """In-place by buffer rebinding (the reference mutates the ndarray).
    mode='raise' behaves as 'clip' — an XLA update cannot raise on
    out-of-bounds indices; 'clip'/'wrap' follow numpy."""
    if not isinstance(a, NDArray):
        raise TypeError("np.put needs an mx.np.ndarray")
    flat = _unwrap(a).reshape(-1)
    n = flat.shape[0]
    idx = jnp.asarray(_unwrap(ind)).reshape(-1)
    idx = idx % n if mode == "wrap" else jnp.clip(idx, -n, n - 1)
    vals = jnp.asarray(_unwrap(v), flat.dtype).reshape(-1)
    if vals.shape[0] < idx.shape[0]:  # numpy cycles short value vectors
        # NB: builtin max is shadowed by the mx.np reduction in this module
        vals = jnp.tile(vals, -(-idx.shape[0] // (vals.shape[0] or 1)))
    a._set_data(flat.at[idx].set(vals[:idx.shape[0]]).reshape(a.shape))


@_np_op("place")
def place(a, mask, vals):
    if not isinstance(a, NDArray):
        raise TypeError("np.place needs an mx.np.ndarray")
    m = jnp.asarray(_unwrap(mask), bool).reshape(-1)
    flat = _unwrap(a).reshape(-1)
    v = jnp.asarray(_unwrap(vals), flat.dtype).reshape(-1)
    n = int(m.sum())
    reps = -(-n // (v.shape[0] or 1))  # builtin max is shadowed here
    vfull = jnp.tile(v, reps)[:flat.shape[0]]
    pos = jnp.cumsum(m) - 1
    a._set_data(jnp.where(m, vfull[pos], flat).reshape(a.shape))


@_np_op("fill_diagonal")
def fill_diagonal(a, val, wrap=False):
    if not isinstance(a, NDArray):
        raise TypeError("np.fill_diagonal needs an mx.np.ndarray")
    a._set_data(jnp.fill_diagonal(_unwrap(a), _unwrap(val), wrap=wrap,
                                  inplace=False))


@_np_op("iscomplexobj")
def iscomplexobj(x):
    return bool(jnp.iscomplexobj(_unwrap_deep(x)))


@_np_op("isrealobj")
def isrealobj(x):
    return bool(jnp.isrealobj(_unwrap_deep(x)))


@_np_op("array_equiv")
def array_equiv(a1, a2):
    return bool(jnp.array_equiv(_unwrap_deep(a1), _unwrap_deep(a2)))
