"""``mx.np.linalg`` — numpy-frontend linear algebra.

Reference: ``python/mxnet/numpy/linalg.py`` (TBV). Thin explicit wrappers
over jnp.linalg that unwrap/rewrap :class:`NDArray` at the boundary and
record on the autograd tape (the bare jnp.linalg module would reject
NDArray arguments outright). 32-bit defaults throughout (x64 disabled).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray import NDArray
from ..ndarray.ndarray import invoke_fn

__all__ = ["norm", "svd", "cholesky", "inv", "det", "slogdet", "eig",
           "eigh", "eigvals", "eigvalsh", "qr", "solve", "lstsq",
           "matrix_rank", "matrix_power", "pinv", "multi_dot",
           "tensorinv", "tensorsolve"]


def _nd(x):
    return x if isinstance(x, NDArray) else NDArray(jnp.asarray(x))


def _call(fn, arrays, **kwargs):
    return invoke_fn(lambda *ts: fn(*ts, **kwargs), [_nd(a) for a in arrays])


def norm(x, ord=None, axis=None, keepdims=False):
    return _call(jnp.linalg.norm, [x], ord=ord, axis=axis, keepdims=keepdims)


def svd(a, full_matrices=True, compute_uv=True, hermitian=False):
    return _call(jnp.linalg.svd, [a], full_matrices=full_matrices,
                 compute_uv=compute_uv, hermitian=hermitian)


def cholesky(a):
    return _call(jnp.linalg.cholesky, [a])


def inv(a):
    return _call(jnp.linalg.inv, [a])


def det(a):
    return _call(jnp.linalg.det, [a])


def slogdet(a):
    return _call(jnp.linalg.slogdet, [a])


def _cpu_call(fn, arrays, **kwargs):
    """Nonsymmetric eigendecomposition has no TPU lowering in XLA — run on
    the host CPU backend and wrap the results."""
    import jax

    cpu0 = jax.local_devices(backend="cpu")[0]
    vals = [jax.device_put(_nd(a)._data, cpu0) for a in arrays]
    res = fn(*vals, **kwargs)
    if isinstance(res, (tuple, list)):
        return tuple(NDArray(r) for r in res)
    return NDArray(res)


def eig(a):
    return _cpu_call(jnp.linalg.eig, [a])


def eigh(a, UPLO="L"):
    return _call(jnp.linalg.eigh, [a], UPLO=UPLO)


def eigvals(a):
    return _cpu_call(jnp.linalg.eigvals, [a])


def eigvalsh(a, UPLO="L"):
    return _call(jnp.linalg.eigvalsh, [a], UPLO=UPLO)


def qr(a, mode="reduced"):
    return _call(jnp.linalg.qr, [a], mode=mode)


def solve(a, b):
    return _call(jnp.linalg.solve, [a, b])


def lstsq(a, b, rcond="warn"):
    rc = None if rcond in ("warn", None) else rcond
    return _call(jnp.linalg.lstsq, [a, b], rcond=rc)


def matrix_rank(a, tol=None, hermitian=False):
    if hermitian:
        raise NotImplementedError(
            "np.linalg.matrix_rank(hermitian=True) is not supported "
            "(jnp.linalg.matrix_rank has no eigh path)")
    return _call(jnp.linalg.matrix_rank, [a], tol=tol)


def matrix_power(a, n):
    return _call(jnp.linalg.matrix_power, [a], n=n)


def pinv(a, rcond=None, hermitian=False):
    # default None -> jnp's dtype-aware cutoff; numpy's 1e-15 constant is
    # a float64 epsilon and would invert fp32-noise singular values
    return _call(jnp.linalg.pinv, [a], rcond=rcond, hermitian=hermitian)


def multi_dot(arrays):
    return _call(lambda *ts: jnp.linalg.multi_dot(ts), list(arrays))


def tensorinv(a, ind=2):
    return _call(jnp.linalg.tensorinv, [a], ind=ind)


def tensorsolve(a, b, axes=None):
    return _call(jnp.linalg.tensorsolve, [a, b], axes=axes)
