"""``mx.np.random`` — numpy-frontend random sampling.

Reference: ``python/mxnet/numpy/random.py`` (TBV). Draws ride the SAME
framework RNG stream as ``mx.nd.random`` (``mxnet_tpu.random.next_key``),
so ``mx.random.seed`` / MXNET_SEED govern both frontends and same-seed
draws are platform-invariant (jax PRNG). Default float dtype is float32
(the mxnet default float), never float64.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _onp

from ..ndarray import NDArray
from ..random import next_key, seed  # noqa: F401  (re-export seed)

__all__ = ["seed", "uniform", "normal", "randint", "rand", "randn",
           "choice", "shuffle", "permutation", "exponential", "gamma",
           "beta", "chisquare", "gumbel", "laplace", "logistic",
           "lognormal", "pareto", "power", "rayleigh", "weibull",
           "multinomial", "multivariate_normal"]


def _pshape(p):
    """Shape of a distribution parameter WITHOUT device conversion."""
    if isinstance(p, NDArray):
        return tuple(p.shape)
    return _onp.shape(p)


def _size(size, *params):
    """Draw shape: explicit ``size`` wins (and must be broadcast-compatible
    with the parameter shapes, as in numpy); otherwise the broadcast of the
    parameters' shapes (each output element gets an INDEPENDENT draw, not
    one scalar draw rescaled)."""
    pshapes = [_pshape(p) for p in params]
    if size is None:
        return jnp.broadcast_shapes(*pshapes) if pshapes else ()
    shape = (size,) if isinstance(size, int) else tuple(size)
    if pshapes and jnp.broadcast_shapes(shape, *pshapes) != shape:
        raise ValueError(
            f"size {shape} is not broadcast-compatible with parameter "
            f"shapes {pshapes} (numpy raises here too)")
    return shape


def _wrap(x, dtype=None, out=None):
    if dtype is not None:
        x = x.astype(dtype)
    if out is not None:
        if not isinstance(out, NDArray):
            raise TypeError("out= must be an mx.np.ndarray")
        out._set_data(x.astype(out.dtype))
        return out
    return NDArray(x)


def _f(x):
    # unwrap NDArray FIRST: jnp.asarray on one would fall back to
    # __iter__/__float__ — a device round-trip per element
    if isinstance(x, NDArray):
        x = x._data
    return jnp.asarray(x, jnp.float32)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None, out=None):
    shape = _size(size, low, high)
    u = jax.random.uniform(next_key(), shape, jnp.float32)
    return _wrap(_f(low) + u * (_f(high) - _f(low)), dtype, out)


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, out=None):
    shape = _size(size, loc, scale)
    return _wrap(_f(loc) + _f(scale)
                 * jax.random.normal(next_key(), shape, jnp.float32), dtype,
                 out)


def randint(low, high=None, size=None, dtype=None, ctx=None, out=None):
    if high is None:
        low, high = 0, low
    r = jax.random.randint(next_key(), _size(size), int(low), int(high),
                           jnp.int32)
    return _wrap(r, dtype, out)


def rand(*size):
    return uniform(size=size or None)


def randn(*size):
    return normal(size=size or None)


def choice(a, size=None, replace=True, p=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.choice: out= is not supported; assign the result')
    arr = a._data if isinstance(a, NDArray) else (
        jnp.arange(a) if isinstance(a, int) else jnp.asarray(a))
    pp = None if p is None else jnp.asarray(
        p._data if isinstance(p, NDArray) else p, jnp.float32)
    r = jax.random.choice(next_key(), arr, _size(size), replace=replace, p=pp)
    return _wrap(r)


def permutation(x):
    arr = (jnp.arange(x) if isinstance(x, int)
           else x._data if isinstance(x, NDArray) else jnp.asarray(x))
    return _wrap(jax.random.permutation(next_key(), arr))


def shuffle(x):
    """In-place along axis 0 (reference semantics: mutates x)."""
    if not isinstance(x, NDArray):
        raise TypeError("np.random.shuffle needs an mx.np.ndarray")
    x._set_data(jax.random.permutation(next_key(), x._data))


def exponential(scale=1.0, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.exponential: out= is not supported; assign the result')
    return _wrap(_f(scale) * jax.random.exponential(
        next_key(), _size(size, scale), jnp.float32))


def gamma(shape, scale=1.0, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.gamma: out= is not supported; assign the result')
    return _wrap(_f(scale) * jax.random.gamma(
        next_key(), _f(shape), _size(size, shape, scale), jnp.float32))


def beta(a, b, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.beta: out= is not supported; assign the result')
    return _wrap(jax.random.beta(next_key(), _f(a), _f(b),
                                 _size(size, a, b), jnp.float32))


def chisquare(df, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.chisquare: out= is not supported; assign the result')
    return _wrap(jax.random.chisquare(next_key(), _f(df),
                                      _size(size, df), jnp.float32))


def gumbel(loc=0.0, scale=1.0, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.gumbel: out= is not supported; assign the result')
    return _wrap(_f(loc) + _f(scale) * jax.random.gumbel(
        next_key(), _size(size, loc, scale), jnp.float32))


def laplace(loc=0.0, scale=1.0, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.laplace: out= is not supported; assign the result')
    return _wrap(_f(loc) + _f(scale) * jax.random.laplace(
        next_key(), _size(size, loc, scale), jnp.float32))


def logistic(loc=0.0, scale=1.0, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.logistic: out= is not supported; assign the result')
    return _wrap(_f(loc) + _f(scale) * jax.random.logistic(
        next_key(), _size(size, loc, scale), jnp.float32))


def lognormal(mean=0.0, sigma=1.0, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.lognormal: out= is not supported; assign the result')
    return _wrap(jnp.exp(_f(mean) + _f(sigma) * jax.random.normal(
        next_key(), _size(size, mean, sigma), jnp.float32)))


def pareto(a, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.pareto: out= is not supported; assign the result')
    return _wrap(jax.random.pareto(next_key(), _f(a), _size(size, a),
                                   jnp.float32) - 1.0)


def power(a, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.power: out= is not supported; assign the result')
    # X = U^(1/a): numpy's power distribution
    u = jax.random.uniform(next_key(), _size(size, a), jnp.float32)
    return _wrap(u ** (1.0 / _f(a)))


def rayleigh(scale=1.0, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.rayleigh: out= is not supported; assign the result')
    u = jax.random.uniform(next_key(), _size(size, scale), jnp.float32,
                           minval=1e-12)
    return _wrap(_f(scale) * jnp.sqrt(-2.0 * jnp.log(u)))


def weibull(a, size=None, ctx=None, out=None):
    if out is not None:
        raise NotImplementedError(
            'np.random.weibull: out= is not supported; assign the result')
    return _wrap(jax.random.weibull_min(
        next_key(), 1.0, _f(a), _size(size, a), jnp.float32))


def multinomial(n, pvals, size=None):
    shape = _size(size)
    pv = _f(pvals)
    k = pv.shape[-1]
    draws = jax.random.categorical(
        next_key(), jnp.log(pv), shape=shape + (int(n),))
    # O(n) counting via flattened bincount — a one_hot of size+(n,k)
    # would allocate n*k device memory for an O(k) result
    flat = draws.reshape(-1, int(n))
    offsets = jnp.arange(flat.shape[0], dtype=draws.dtype)[:, None] * k
    counts = jnp.bincount((flat + offsets).reshape(-1),
                          length=flat.shape[0] * k)
    return _wrap(counts.reshape(shape + (k,)).astype(jnp.int32))


def multivariate_normal(mean, cov, size=None, check_valid="warn", tol=1e-8):
    m = jnp.asarray(mean._data if isinstance(mean, NDArray) else mean,
                    jnp.float32)
    c = jnp.asarray(cov._data if isinstance(cov, NDArray) else cov,
                    jnp.float32)
    return _wrap(jax.random.multivariate_normal(
        next_key(), m, c, _size(size) or None, jnp.float32))
