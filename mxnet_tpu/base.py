"""Base utilities: dtype handling, env-var config, error types.

TPU-native re-design of the reference's ``python/mxnet/base.py`` (ctypes FFI
bootstrap; reference path TBV — mount empty at survey time, see SURVEY.md §0).
There is no C ABI here: the "backend" is JAX/XLA over PJRT, so this module only
carries the pieces of base.py that still make sense — dtype tables, the
``MXNET_*`` env-var config layer (SURVEY.md §5.6 tier 1), and exception types.
"""
from __future__ import annotations

import os
from typing import Any

import numpy as np

__all__ = [
    "MXNetError",
    "mx_real_t",
    "string_types",
    "numeric_types",
    "integer_types",
    "get_env",
    "set_env",
    "dtype_np",
    "dtype_name",
    "capped_backoff",
    "configure_socket_keepalive",
]


def capped_backoff(attempt: int, base_interval: float,
                   max_interval: float) -> float:
    """Capped exponential backoff with full-range jitter: attempt 0 →
    ~base_interval, doubling up to max_interval, scaled by a uniform draw
    in [0.5, 1.0]. The ONE retry-delay policy shared by the PS client and
    the serving plane (client reconnects, replica-pool restarts): jitter
    decorrelates a fleet hammering a restarting peer, and sharing the
    helper keeps the two planes from ever drifting apart."""
    import random

    delay = min(float(max_interval), float(base_interval) * (2.0 ** attempt))
    return delay * (0.5 + random.random() / 2.0)


def configure_socket_keepalive(sock, idle: int = 30, interval: int = 5,
                               count: int = 3) -> None:
    """Enable TCP keepalive on ``sock`` (half-open-connection detection).

    The ONE keepalive policy shared by the PS client, the serve client, and
    the elastic heartbeater: a peer that vanished without a FIN (SIGKILL'd
    VM, dropped tunnel) is detected by the kernel after
    ``idle + interval*count`` seconds instead of whenever the OS default
    (often hours) gives up. The per-platform TCP_KEEP* constants are probed
    — missing ones just fall back to the system defaults; any OSError is
    swallowed because keepalive is an optimization, never a correctness
    requirement (the RPC layers still carry their own timeouts)."""
    import socket as _socket

    try:
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_KEEPALIVE, 1)
        for opt, val in (("TCP_KEEPIDLE", idle), ("TCP_KEEPINTVL", interval),
                         ("TCP_KEEPCNT", count)):
            if hasattr(_socket, opt):
                sock.setsockopt(_socket.IPPROTO_TCP,
                                getattr(_socket, opt), val)
    except OSError:
        pass


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with reference ``MXNetError``)."""


class GraphAnalysisError(MXNetError, ValueError):
    """Structured graph-analysis failure with node attribution.

    Raised by shape/type inference and ``bind(lint="error")`` instead of an
    opaque tracer exception: ``node``/``op``/``input_shapes`` name exactly
    where the graph broke. Subclasses ValueError so callers that caught the
    old ad-hoc inference ValueErrors keep working.
    """

    def __init__(self, message, node=None, op=None, rule_id=None,
                 input_shapes=None, findings=None):
        super().__init__(message)
        self.node = node
        self.op = op
        self.rule_id = rule_id
        self.input_shapes = input_shapes
        self.findings = findings or []


# Default real type, matching the reference's mshadow default_real_t = float32.
mx_real_t = np.float32

string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)

# dtype name <-> numpy dtype table. The reference keeps int codes in
# include/mxnet/base.h (mshadow TypeFlag); here names are canonical and the
# int codes are kept only for checkpoint-format compat (ndarray save/load).
_DTYPE_NAME_TO_NP = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "bfloat16": None,  # filled lazily from ml_dtypes to avoid hard dep at import
    "uint8": np.uint8,
    "int32": np.int32,
    "int8": np.int8,
    "int64": np.int64,
    "bool": np.bool_,
    "int16": np.int16,
    "uint16": np.uint16,
    "uint32": np.uint32,
    "uint64": np.uint64,
}

# mshadow TypeFlag int codes (reference include/mxnet/base.h, TBV) — used by the
# binary .params format so checkpoints stay loadable across frameworks.
DTYPE_TO_CODE = {
    "float32": 0,
    "float64": 1,
    "float16": 2,
    "uint8": 3,
    "int32": 4,
    "int8": 5,
    "int64": 6,
    "bool": 7,
    "int16": 8,
    "uint16": 9,
    "uint32": 10,
    "uint64": 11,
    "bfloat16": 12,
}
CODE_TO_DTYPE = {v: k for k, v in DTYPE_TO_CODE.items()}


def _bfloat16():
    import ml_dtypes

    return ml_dtypes.bfloat16


def dtype_np(dtype: Any):
    """Normalize a user-facing dtype (str/np.dtype/type/None) to a numpy dtype."""
    if dtype is None:
        return np.dtype(mx_real_t)
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return np.dtype(_bfloat16())
        if dtype not in _DTYPE_NAME_TO_NP:
            raise TypeError(f"unknown dtype {dtype!r}")
        return np.dtype(_DTYPE_NAME_TO_NP[dtype])
    return np.dtype(dtype)


def dtype_name(dtype: Any) -> str:
    """Canonical string name for a dtype."""
    return np.dtype(dtype).name if not isinstance(dtype, str) else dtype


# ---------------------------------------------------------------------------
# Env-var config layer (reference: dmlc::GetEnv over MXNET_* — SURVEY.md §5.6).
# Reads accept both the historical MXNET_ prefix and no prefix.
# ---------------------------------------------------------------------------

def get_env(name: str, default=None, typ=str):
    """Read an ``MXNET_*`` config env var with type coercion.

    Mirrors the reference's dmlc::GetEnv tier of its 3-tier config system.
    """
    raw = os.environ.get(name)
    if raw is None and not name.startswith("MXNET_"):
        raw = os.environ.get("MXNET_" + name)
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() not in ("0", "false", "off", "")
    return typ(raw)


def set_env(name: str, value) -> None:
    os.environ[name] = str(value)
