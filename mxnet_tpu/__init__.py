"""mxnet_tpu — a TPU-native deep-learning framework with the capabilities of
Apache-MXNet-1.x-class systems (the reference, parkchanyong/mxnet).

Built from scratch on JAX/XLA/Pallas/pjit over PJRT. See SURVEY.md for the
layer map of the reference and README.md for the architecture of this build.

Import as ``import mxnet_tpu as mx`` — the namespace mirrors the reference's
``import mxnet as mx`` surface: ``mx.nd``, ``mx.sym``, ``mx.autograd``,
``mx.gluon``, ``mx.cpu()/mx.gpu()/mx.tpu()``, ``mx.kv``, ``mx.io``, …
"""
from __future__ import annotations

__version__ = "0.1.0"

import jax as _jax

# fp32 matmuls are true fp32 (reference parity: cuBLAS fp32 GEMM). The fast
# MXU path is bf16 *inputs* (AMP / bf16 params), which is single-pass
# regardless of this setting — so perf work happens in dtype policy, not here.
# Override via MXNET_MATMUL_PRECISION=default|high|highest.
import os as _os

_jax.config.update("jax_default_matmul_precision",
                   _os.environ.get("MXNET_MATMUL_PRECISION", "highest"))

# MXNET_FORCE_PLATFORM=cpu|tpu: pin the jax backend at import time. Needed
# because this image preloads jax with JAX_PLATFORMS=axon via sitecustomize,
# so the plain env var is too late for subprocesses (example-script CI runs
# tiny configs on CPU this way; see tests/conftest.py for the same trick).
if _os.environ.get("MXNET_FORCE_PLATFORM"):
    _jax.config.update("jax_platforms", _os.environ["MXNET_FORCE_PLATFORM"])

# Persistent XLA compilation cache (works through the axon remote-compile
# tunnel; measured: repeat compiles drop from minutes to seconds). Keyed by
# HLO hash, so code changes can't serve stale binaries. MXNET_COMPILE_CACHE=0
# disables; MXNET_COMPILE_CACHE_DIR overrides the location.
if _os.environ.get("MXNET_COMPILE_CACHE", "1") != "0":
    _cache_dir = _os.environ.get(
        "MXNET_COMPILE_CACHE_DIR",
        _os.path.expanduser("~/.cache/mxnet_tpu_jax"))
    try:
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except (OSError, AttributeError):
        pass

from .base import MXNetError, get_env  # noqa: F401
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus, num_tpus  # noqa: F401
from . import ops  # noqa: F401  (registers the operator library)
from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from .ndarray import NDArray  # noqa: F401
from . import autograd  # noqa: F401
from . import random  # noqa: F401


def __getattr__(name):
    # Lazy submodule loading keeps `import mxnet_tpu` fast and cycle-free.
    import importlib

    lazy = {
        "sym": ".symbol",
        "symbol": ".symbol",
        "gluon": ".gluon",
        "optimizer": ".optimizer",
        "lr_scheduler": ".optimizer.lr_scheduler",
        "metric": ".metric",
        "initializer": ".initializer",
        "init": ".initializer",
        "io": ".io",
        "recordio": ".io.recordio",
        "image": ".image",
        "kvstore": ".kvstore",
        "kv": ".kvstore",
        "module": ".module",
        "mod": ".module",
        "rnn": ".rnn",
        "callback": ".callback",
        "profiler": ".profiler",
        "model": ".model",
        "runtime": ".runtime",
        "registry": ".registry",
        "test_utils": ".test_utils",
        "executor": ".executor",
        "amp": ".amp",
        "parallel": ".parallel",
        "models": ".models",
        "contrib": ".contrib",
        "util": ".util",
        "np": ".numpy",
        "npx": ".numpy_extension",
        "operator": ".operator",
        "monitor": ".monitor",
        "mon": ".monitor",
        "obs": ".obs",
        "platform": ".platform",
        "serve": ".serve",
        "native": ".native",
        "viz": ".visualization",
        "visualization": ".visualization",
        "engine": ".engine",
        "attribute": ".attribute",
        "subgraph": ".subgraph",
        "name": ".name",
    }
    if name in lazy:
        mod = importlib.import_module(lazy[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu' has no attribute {name!r}")
