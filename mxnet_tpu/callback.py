"""Training callbacks (reference python/mxnet/callback.py — TBV)."""
from __future__ import annotations

import logging
import time
from collections import namedtuple

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric", "module_checkpoint", "ProgressBar",
           "BatchEndParam"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


class Speedometer:
    """Log samples/sec every ``frequent`` batches (the number the baseline
    quotes — reference callback.Speedometer).

    Uses ``time.monotonic()`` (wall-clock steps from NTP would corrupt the
    rate) and guards the zero-elapsed division (``frequent=1`` fires on the
    first measured batch, which can land in the same clock tick). The rate
    is also published as the ``training.samples_per_sec`` gauge when obs
    telemetry is on, so it shows up in ``tools/trace_report.py``.
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        from . import obs

        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                elapsed = time.monotonic() - self.tic
                speed = (self.frequent * self.batch_size
                         / max(elapsed, 1e-9))
                obs.set_gauge("training.samples_per_sec", speed)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s" % (
                        param.epoch, count, speed,
                        "\t".join(f"{n}={v:f}" for n, v in name_value))
                else:
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" % (
                        param.epoch, count, speed)
                logging.info(msg)
                self.tic = time.monotonic()
        else:
            self.init = True
            self.tic = time.monotonic()


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback (reference do_checkpoint)."""

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            from .model import save_checkpoint

            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


module_checkpoint = do_checkpoint


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            logging.info("Iter[%d] Batch[%d] Train-%s", param.epoch, param.nbatch,
                         "\t".join(f"{n}={v:f}" for n, v in name_value))
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class ProgressBar:
    """Console progress bar callback (reference callback.ProgressBar)."""

    def __init__(self, total, length=80):
        self.total = max(1, int(total))
        self.length = int(length)

    def __call__(self, param):
        count = getattr(param, "nbatch", 0)
        filled = int(round(self.length * min(count, self.total) / self.total))
        bar = "=" * filled + "-" * (self.length - filled)
        import sys

        sys.stdout.write(f"\r[{bar}] {count}/{self.total}")
        sys.stdout.flush()
        if count >= self.total:
            sys.stdout.write("\n")
