"""``mx.image`` — image io/augmentation API.

Reference: ``python/mxnet/image/image.py`` (TBV — SURVEY.md §2.3). The
reference decodes with OpenCV; here PIL (host) + jnp (device). ImageIter
wraps the RecordIO pipeline.
"""
from __future__ import annotations

import io as _io
import os

import numpy as np

from .ndarray import NDArray, array as nd_array

__all__ = ["imread", "imdecode", "imresize", "fixed_crop", "center_crop",
           "random_crop", "resize_short", "color_normalize", "HorizontalFlipAug",
           "CastAug", "ColorNormalizeAug", "ResizeAug", "RandomCropAug",
           "CenterCropAug", "CreateAugmenter", "ImageIter"]


def _to_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)


def imread(filename, flag=1, to_rgb=True):
    from PIL import Image

    img = Image.open(filename)
    img = img.convert("RGB" if flag else "L")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return nd_array(arr)


def imdecode(buf, flag=1, to_rgb=True):
    from PIL import Image

    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = Image.open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return nd_array(arr)


def imresize(src, w, h, interp=1):
    from PIL import Image

    arr = _to_np(src).astype(np.uint8)
    mode = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC}.get(interp,
                                                                       Image.BILINEAR)
    sq = arr.shape[-1] == 1
    pil = Image.fromarray(arr.squeeze(-1) if sq else arr)
    out = np.asarray(pil.resize((w, h), mode))
    if sq:
        out = out[:, :, None]
    return nd_array(out)


def resize_short(src, size, interp=1):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if h < w:
        nh, nw = size, int(w * size / h)
    else:
        nh, nw = int(h * size / w), size
    return imresize(src, nw, nh, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    arr = _to_np(src)[y0:y0 + h, x0:x0 + w]
    out = nd_array(arr)
    if size is not None and (w, h) != tuple(size):
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=1):
    arr = _to_np(src)
    H, W = arr.shape[:2]
    w, h = size
    x0, y0 = max((W - w) // 2, 0), max((H - h) // 2, 0)
    return fixed_crop(src, x0, y0, min(w, W), min(h, H), size, interp), (x0, y0, w, h)


def random_crop(src, size, interp=1):
    arr = _to_np(src)
    H, W = arr.shape[:2]
    w, h = min(size[0], W), min(size[1], H)
    x0 = np.random.randint(0, W - w + 1)
    y0 = np.random.randint(0, H - h + 1)
    return fixed_crop(src, x0, y0, w, h, size, interp), (x0, y0, w, h)


def color_normalize(src, mean, std=None):
    arr = _to_np(src).astype(np.float32)
    arr = arr - _to_np(mean)
    if std is not None:
        arr = arr / _to_np(std)
    return nd_array(arr)


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, (self.size, self.size), self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, (self.size, self.size), self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            return nd_array(np.ascontiguousarray(_to_np(src)[:, ::-1]))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean, self.std = np.asarray(mean, np.float32), \
            np.asarray(std, np.float32)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize))
    crop_size = data_shape[2] if len(data_shape) == 3 else data_shape[1]
    if rand_crop:
        auglist.append(RandomCropAug(crop_size))
    else:
        auglist.append(CenterCropAug(crop_size))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375], np.float32)
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std if std is not None else 1.0))
    return auglist


class ImageIter:
    """Python-side image iterator over .rec or .lst files (reference
    mx.image.ImageIter; the C++-pipeline analog is io.ImageRecordIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None, path_imglist=None,
                 path_root="", shuffle=False, aug_list=None, label_width=1,
                 **kwargs):
        from .io.io import ImageRecordIter

        if path_imgrec is None:
            raise ValueError("path_imgrec is required (list-file mode TBD)")
        self._inner = ImageRecordIter(
            path_imgrec=path_imgrec, data_shape=data_shape, batch_size=batch_size,
            shuffle=shuffle, label_width=label_width, **kwargs)
        self.batch_size = batch_size
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def __iter__(self):
        return self

    def reset(self):
        self._inner.reset()

    def __next__(self):
        return self._inner.next()

    next = __next__
