"""``mx.npx`` — numpy-extension namespace (nn ops with numpy arrays).

Reference: ``python/mxnet/numpy_extension/`` (npx.relu / npx.batch_norm /
set_np — TBV). Delegates to the registered op library.
"""
from __future__ import annotations

from .ops import has_op
from .ndarray import invoke

__all__ = ["set_np", "reset_np", "is_np_array", "use_np"]

_np_mode = {"array": False, "shape": False}

_ALIASES = {
    "relu": "Activation",
    "sigmoid": "sigmoid",
    "softmax": "softmax",
    "log_softmax": "log_softmax",
    "batch_norm": "BatchNorm",
    "layer_norm": "LayerNorm",
    "fully_connected": "FullyConnected",
    "convolution": "Convolution",
    "pooling": "Pooling",
    "embedding": "Embedding",
    "topk": "topk",
    "pick": "pick",
    "one_hot": "one_hot",
    "rnn": "RNN",
    "dropout": "Dropout",
    "gelu": "gelu",
    "sequence_mask": "SequenceMask",
    "gamma": "gamma",
}


def set_np(shape=True, array=True, dtype=False):
    _np_mode["array"] = array
    _np_mode["shape"] = shape


def reset_np():
    _np_mode["array"] = False
    _np_mode["shape"] = False


def is_np_array():
    return _np_mode["array"]


def use_np(fn):
    return fn


def __getattr__(name):
    op_name = _ALIASES.get(name, name)
    if has_op(op_name):
        def f(*inputs, **kwargs):
            from .ndarray import NDArray

            tensors = []
            rest = list(inputs)
            while rest and isinstance(rest[0], NDArray):
                tensors.append(rest.pop(0))
            if name == "relu" and "act_type" not in kwargs:
                kwargs["act_type"] = "relu"
            return invoke(op_name, tensors, kwargs)

        f.__name__ = name
        globals()[name] = f
        return f
    raise AttributeError(f"module 'mxnet_tpu.numpy_extension' has no attribute {name!r}")
