"""``mx.npx`` — numpy-extension namespace.

Reference: ``python/mxnet/numpy_extension/`` (TBV — SURVEY.md §2.3): the
nn/operator surface for numpy-mode code — ``npx.relu``, ``npx.batch_norm``,
``npx.convolution`` … plus ``set_np``/``use_np`` mode switches and context
re-exports. Round 2 shipped a pure alias delegate; this version defines the
surface EXPLICITLY with the reference's signatures (names, arg order,
defaults), delegating compute to the registered op library so autograd /
hybridize / sharding all work unchanged.
"""
from __future__ import annotations

from .context import cpu, gpu, tpu, current_context, num_gpus, num_tpus  # noqa: F401
from .ndarray import NDArray, invoke, load, save, waitall  # noqa: F401
from .ops import get_op, has_op

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape", "use_np",
           "use_np_array", "use_np_shape", "relu", "sigmoid", "softmax",
           "log_softmax", "masked_softmax", "masked_log_softmax", "gelu",
           "leaky_relu", "activation", "batch_norm", "layer_norm",
           "group_norm", "instance_norm", "l2_normalization",
           "fully_connected", "convolution", "deconvolution", "pooling",
           "dropout", "embedding", "rnn", "one_hot", "pick", "topk",
           "sequence_mask", "arange_like", "broadcast_like", "gather_nd",
           "scatter_nd", "shape_array", "reshape_like", "slice",
           "smooth_l1", "ctc_loss", "multibox_prior", "multibox_target",
           "multibox_detection", "box_nms", "roi_align", "cpu", "gpu",
           "tpu", "current_context", "num_gpus", "num_tpus", "load", "save",
           "waitall"]

_np_mode = {"array": False, "shape": False}


def set_np(shape=True, array=True, dtype=False):
    """Enable numpy semantics globally (reference npx.set_np)."""
    _np_mode["array"] = array
    _np_mode["shape"] = shape


def reset_np():
    _np_mode["array"] = False
    _np_mode["shape"] = False


def is_np_array():
    return _np_mode["array"]


def is_np_shape():
    return _np_mode["shape"]


def use_np(fn):
    """Decorator form (reference: activates np semantics for the callable;
    here np semantics are always available, so this is identity)."""
    return fn


use_np_array = use_np
use_np_shape = use_np


def _call(op_name, tensors, kwargs):
    return invoke(get_op(op_name), list(tensors), kwargs)


# --- activations ----------------------------------------------------------

def relu(data):
    return _call("relu", [data], {})


def sigmoid(data):
    return _call("sigmoid", [data], {})


def gelu(data, approximation="erf"):
    return _call("gelu", [data], {"approximation": approximation})


def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, **kw):
    ins = [data] + ([gamma] if gamma is not None else [])
    return _call("LeakyReLU", ins, {"act_type": act_type, "slope": slope})


def activation(data, act_type="relu"):
    return _call("Activation", [data], {"act_type": act_type})


def softmax(data, length=None, axis=-1, temperature=None, use_length=False):
    kw = {"axis": axis}
    if temperature is not None:
        kw["temperature"] = temperature
    if use_length and length is not None:
        kw["use_length"] = True
        return _call("softmax", [data, length], kw)
    return _call("softmax", [data], kw)


def log_softmax(data, axis=-1, temperature=None):
    kw = {"axis": axis}
    if temperature is not None:
        kw["temperature"] = temperature
    return _call("log_softmax", [data], kw)


def masked_softmax(data, mask=None, axis=-1, temperature=1.0):
    if mask is None:
        return softmax(data, axis=axis)
    import jax.numpy as jnp

    from .ndarray.ndarray import invoke_fn

    def pure(d, m):
        neg = jnp.asarray(-1e30, d.dtype)
        s = jnp.where(m.astype(bool), d / temperature, neg)
        out = jnp.exp(s - jnp.max(s, axis=axis, keepdims=True))
        out = out * m.astype(out.dtype)
        denom = jnp.sum(out, axis=axis, keepdims=True)
        return out / jnp.maximum(denom, 1e-30)

    return invoke_fn(pure, [data, mask])


def masked_log_softmax(data, mask=None, axis=-1, temperature=1.0):
    import jax.numpy as jnp

    from .ndarray.ndarray import invoke_fn

    if mask is None:
        return log_softmax(data, axis=axis)

    def pure(d, m):
        neg = jnp.asarray(-1e30, d.dtype)
        s = jnp.where(m.astype(bool), d / temperature, neg)
        lse = jnp.log(jnp.sum(jnp.exp(
            s - jnp.max(s, axis=axis, keepdims=True)), axis=axis,
            keepdims=True)) + jnp.max(s, axis=axis, keepdims=True)
        out = s - lse
        return jnp.where(m.astype(bool), out, neg)

    return invoke_fn(pure, [data, mask])


# --- normalization --------------------------------------------------------

def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1):
    return _call("BatchNorm", [x, gamma, beta, running_mean, running_var],
                 {"eps": eps, "momentum": momentum, "fix_gamma": fix_gamma,
                  "use_global_stats": use_global_stats,
                  "output_mean_var": output_mean_var, "axis": axis})


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _call("LayerNorm", [data, gamma, beta],
                 {"axis": axis, "eps": eps})


def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    return _call("GroupNorm", [data, gamma, beta],
                 {"num_groups": num_groups, "eps": eps})


def instance_norm(data, gamma, beta, eps=1e-3):
    return _call("InstanceNorm", [data, gamma, beta], {"eps": eps})


def l2_normalization(data, eps=1e-10, mode="instance"):
    return _call("L2Normalization", [data], {"eps": eps, "mode": mode})


# --- layers ---------------------------------------------------------------

def fully_connected(x, weight, bias=None, num_hidden=1, no_bias=False,
                    flatten=True):
    ins = [x, weight] + ([] if bias is None else [bias])
    return _call("FullyConnected", ins,
                 {"num_hidden": num_hidden, "no_bias": no_bias or bias is None,
                  "flatten": flatten})


def convolution(data=None, weight=None, bias=None, kernel=(1, 1),
                stride=(1, 1), dilate=(1, 1), pad=(0, 0), num_filter=1,
                num_group=1, no_bias=False, layout="NCHW", **kw):
    ins = [data, weight] + ([] if bias is None else [bias])
    return _call("Convolution", ins,
                 {"kernel": kernel, "stride": stride, "dilate": dilate,
                  "pad": pad, "num_filter": num_filter,
                  "num_group": num_group,
                  "no_bias": no_bias or bias is None, "layout": layout})


def deconvolution(data=None, weight=None, bias=None, **kw):
    ins = [data, weight] + ([] if bias is None else [bias])
    if bias is None:
        kw["no_bias"] = True
    return _call("Deconvolution", ins, kw)


def pooling(data, kernel=(1, 1), stride=None, pad=None, pool_type="max",
            global_pool=False, **kw):
    kwargs = {"kernel": kernel, "pool_type": pool_type,
              "global_pool": global_pool}
    if stride is not None:
        kwargs["stride"] = stride
    if pad is not None:
        kwargs["pad"] = pad
    return _call("Pooling", [data], kwargs)


def dropout(data, p=0.5, mode="training", **kw):
    return _call("Dropout", [data], {"p": p, "mode": mode})


def embedding(data, weight, input_dim=1, output_dim=1, dtype="float32",
              sparse_grad=False):
    return _call("Embedding", [data, weight],
                 {"input_dim": input_dim, "output_dim": output_dim,
                  "dtype": dtype})


def rnn(data=None, parameters=None, state=None, state_cell=None, mode="lstm",
        state_size=1, num_layers=1, bidirectional=False, p=0.0,
        state_outputs=False, **kw):
    ins = [data, parameters, state] + ([state_cell]
                                       if state_cell is not None else [])
    return _call("RNN", ins,
                 {"mode": mode, "state_size": state_size,
                  "num_layers": num_layers, "bidirectional": bidirectional,
                  "p": p, "state_outputs": state_outputs, **kw})


# --- indexing / shape -----------------------------------------------------

def one_hot(data, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    return _call("one_hot", [data], {"depth": depth, "on_value": on_value,
                                     "off_value": off_value, "dtype": dtype})


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    return _call("pick", [data, index],
                 {"axis": axis, "mode": mode, "keepdims": keepdims})


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    return _call("topk", [data], {"axis": axis, "k": k, "ret_typ": ret_typ,
                                  "is_ascend": is_ascend, "dtype": dtype})


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    ins = [data] + ([sequence_length] if sequence_length is not None else [])
    return _call("SequenceMask", ins,
                 {"use_sequence_length": use_sequence_length or
                  sequence_length is not None, "value": value, "axis": axis})


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    return _call("_contrib_arange_like", [data],
                 {"start": start, "step": step, "repeat": repeat,
                  "axis": axis})


def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    return _call("broadcast_like", [lhs, rhs],
                 {"lhs_axes": lhs_axes, "rhs_axes": rhs_axes})


def gather_nd(data, indices):
    return _call("gather_nd", [data, indices], {})


def scatter_nd(data, indices, shape):
    return _call("scatter_nd", [data, indices], {"shape": shape})


def shape_array(data):
    return _call("shape_array", [data], {})


def reshape_like(lhs, rhs, **kw):
    from .ndarray.ndarray import invoke_fn

    return invoke_fn(lambda a, b: a.reshape(b.shape), [lhs, rhs])


def slice(data, begin, end, step=None):  # noqa: A001 - reference name
    kw = {"begin": begin, "end": end}
    if step is not None:
        kw["step"] = step
    return _call("slice", [data], kw)


def smooth_l1(data, scalar=1.0):
    return _call("smooth_l1", [data], {"scalar": scalar})


def ctc_loss(data, label, data_lengths=None, label_lengths=None, **kw):
    ins = [data, label]
    if data_lengths is not None:
        ins.append(data_lengths)
        kw["use_data_lengths"] = True
    if label_lengths is not None:
        if data_lengths is None:
            raise ValueError("label_lengths requires data_lengths")
        ins.append(label_lengths)
        kw["use_label_lengths"] = True
    return _call("ctc_loss", ins, kw)


# --- contrib detection ops ------------------------------------------------

def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False, **kw):
    return _call("_contrib_MultiBoxPrior", [data],
                 {"sizes": sizes, "ratios": ratios, "clip": clip, **kw})


def multibox_target(anchor, label, cls_pred, **kw):
    return _call("_contrib_MultiBoxTarget", [anchor, label, cls_pred], kw)


def multibox_detection(cls_prob, loc_pred, anchor, **kw):
    return _call("_contrib_MultiBoxDetection", [cls_prob, loc_pred, anchor],
                 kw)


def box_nms(data, **kw):
    return _call("_contrib_box_nms", [data], kw)


def roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0, **kw):
    return _call("_contrib_ROIAlign", [data, rois],
                 {"pooled_size": pooled_size, "spatial_scale": spatial_scale,
                  **kw})


def __getattr__(name):
    """Fallback: any other registered op resolves by name (PascalCase legacy
    names included), so the namespace stays a superset of the reference."""
    if has_op(name):
        def f(*inputs, **kwargs):
            tensors = []
            rest = list(inputs)
            while rest and isinstance(rest[0], NDArray):
                tensors.append(rest.pop(0))
            return invoke(name, tensors, kwargs)

        f.__name__ = name
        globals()[name] = f
        return f
    raise AttributeError(
        f"module 'mxnet_tpu.numpy_extension' has no attribute {name!r}")
