"""Device context: ``mx.cpu()``, ``mx.gpu()``, ``mx.tpu()``.

Re-design of the reference ``python/mxnet/context.py`` + ``include/mxnet/base.h``
``Context{dev_type, dev_id}`` (paths TBV — mount empty, SURVEY.md §0) for TPU:

- A ``Context`` names a *logical* device and resolves to a ``jax.Device``.
- ``mx.tpu(i)`` is the new first-class accelerator context (SURVEY.md §2.3
  "add mx.tpu(i) here").
- ``mx.gpu(i)`` **aliases to the accelerator** when no real GPU exists, so
  reference training scripts written against ``mx.gpu()`` run unmodified on a
  TPU pod (BASELINE.json north star).
- There is no storage manager / stream pool here: PJRT owns device memory and
  XLA owns streams (reference L0 `src/storage/` is subsumed — SURVEY.md §2.1).
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_gpus", "num_tpus"]


class Context:
    """A logical device. Usable as a context manager to set the default device."""

    # dev_type int codes kept for checkpoint/string compat with the reference.
    devtype2num = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    devnum2type = {v: k for k, v in devtype2num.items()}

    _default = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devtype2num:
            raise ValueError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- identity ----------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devtype2num[self.device_type]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- JAX resolution ----------------------------------------------------
    def jax_device(self) -> jax.Device:
        """Resolve to a concrete jax.Device.

        gpu/tpu both resolve to the process's accelerator devices; gpu is an
        alias kept so reference scripts (`ctx=mx.gpu(0)`) run unmodified.
        """
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = _cpu_devices()
        else:
            devs = _accel_devices()
            if not devs:  # CPU-only process (CI): accelerator ctx falls back
                devs = _cpu_devices()
        return devs[self.device_id % len(devs)]

    # -- default-context scope --------------------------------------------
    def __enter__(self):
        stack = getattr(Context._default, "stack", None)
        if stack is None:
            stack = Context._default.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default.stack.pop()

    @classmethod
    def default_ctx(cls) -> "Context":
        stack = getattr(cls._default, "stack", None)
        if stack:
            return stack[-1]
        global _DEFAULT
        if _DEFAULT is None:
            # Resolved on first use, NOT at import: touching jax.devices()
            # at import time would initialize the XLA backend and break the
            # create-kvstore-before-arrays contract jax.distributed needs.
            _DEFAULT = Context("tpu", 0) if _accel_devices() else Context("cpu", 0)
        return _DEFAULT


def _cpu_devices():
    # local_devices, not devices(): under jax.distributed a Context must name
    # a process-addressable device (reference: each worker owns its own GPUs)
    if jax.default_backend() != "cpu":
        return jax.local_devices(backend="cpu")
    return jax.local_devices()


_ACCEL_CACHE: Optional[list] = None


def _accel_devices():
    global _ACCEL_CACHE
    if _ACCEL_CACHE is None:
        devs = jax.local_devices()
        _ACCEL_CACHE = [d for d in devs if d.platform not in ("cpu",)]
    return _ACCEL_CACHE


_DEFAULT: Optional[Context] = None  # lazily resolved by default_ctx()


def cpu(device_id: int = 0) -> Context:
    """CPU context (reference mx.cpu())."""
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    """Pinned-host-memory context. On PJRT this is plain host memory."""
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    """Accelerator context; alias of tpu() on TPU machines (script compat)."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    """TPU context — the native accelerator context of this framework."""
    return Context("tpu", device_id)


def num_gpus() -> int:
    """Number of accelerator devices (reference mx.context.num_gpus())."""
    return len(_accel_devices())


def num_tpus() -> int:
    return len(_accel_devices())


def current_context() -> Context:
    """The innermost `with ctx:` scope, else the process default (cpu or tpu)."""
    return Context.default_ctx()


