"""Imperative autograd: define-by-run tape over jax.vjp.

Reference: ``src/imperative/imperative.cc`` (``Imperative::RecordOp/Backward``,
AGInfo tape nodes) + ``python/mxnet/autograd.py`` (paths TBV — SURVEY.md §2.1).

TPU-native redesign: instead of building an NNVM gradient graph and replaying
FCompute backward kernels through an engine, each recorded op stores the
``jax.vjp`` of its **own pure function** — forward runs eagerly, and
``backward()`` walks the tape calling the stored vjps. The residuals live in
PJRT buffers exactly like cuDNN workspace saved-tensors do in the reference.
``create_graph=True`` (higher-order grad) re-enters recording during the
backward walk, so grad-of-grad works through the same machinery.
"""
from __future__ import annotations

import threading
import weakref
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["Function", "record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "backward", "grad", "mark_variables", "set_recording",
           "set_training"]


class _AGState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _AGState()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    old, _STATE.recording = _STATE.recording, bool(flag)
    return old


def set_training(flag: bool) -> bool:
    old, _STATE.training = _STATE.training, bool(flag)
    return old


class _Scope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training

    def __enter__(self):
        if self._rec is not None:
            self._old_rec = set_recording(self._rec)
        if self._train is not None:
            self._old_train = set_training(self._train)
        return self

    def __exit__(self, *exc):
        if self._rec is not None:
            set_recording(self._old_rec)
        if self._train is not None:
            set_training(self._old_train)


def record(train_mode: bool = True) -> _Scope:
    """``with autograd.record():`` — turn on recording (+train mode)."""
    return _Scope(True, train_mode)


def pause(train_mode: bool = False) -> _Scope:
    return _Scope(False, train_mode)


def train_mode() -> _Scope:
    return _Scope(None, True)


def predict_mode() -> _Scope:
    return _Scope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

class _Node:
    """One recorded op. parents[i] is (node, out_index) or None per input.

    ``closed``/``primals`` keep the node's pure function and its primal
    inputs so create_graph=True can RE-DERIVE the vjp as recorded ops: the
    stored ``vjp_fn`` closes over residuals, hiding the primal dependence —
    differentiating through it would yield zero for d(grad)/d(primal)."""

    __slots__ = ("vjp_fn", "parents", "out_avals", "outputs", "name",
                 "out_is_tuple", "closed", "primals")

    def __init__(self, vjp_fn, parents, out_avals, name, out_is_tuple=False,
                 closed=None, primals=None):
        self.vjp_fn = vjp_fn
        self.parents = parents
        self.out_avals = out_avals  # list of (shape, dtype)
        self.outputs = None  # weakrefs set lazily for variable deposit
        self.name = name
        self.out_is_tuple = out_is_tuple
        self.closed = closed
        self.primals = primals


class _VarNode:
    """A leaf created by attach_grad; deposits cotangents into .grad."""

    __slots__ = ("ref", "name")

    def __init__(self, arr):
        self.ref = weakref.ref(arr)
        self.name = "var"


def _mark_variable(arr) -> None:
    arr._ag_node = (_VarNode(arr), 0)


def mark_variables(variables, gradients, grad_reqs="write"):
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, r in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = r
        _mark_variable(v)


def _record_op(opdef, inputs, datas, kwargs):
    """Called by ndarray.invoke while recording. Computes forward via jax.vjp
    and returns wrapped outputs with tape nodes attached."""
    from .ndarray.ndarray import NDArray, _wrap_result

    parents = []
    any_parent = False
    for x in inputs:
        if isinstance(x, NDArray) and x._ag_node is not None:
            parents.append(x._ag_node)
            any_parent = True
        else:
            parents.append(None)
    if not any_parent:
        result = opdef.fn(*datas, **kwargs)
        return _wrap_result(result, inputs)

    # Only differentiate w.r.t. float inputs; pass others through as closures.
    diff_idx = [i for i, d in enumerate(datas)
                if hasattr(d, "dtype") and jnp.issubdtype(jnp.asarray(d).dtype, jnp.inexact)]
    if not diff_idx:
        result = opdef.fn(*datas, **kwargs)
        return _wrap_result(result, inputs)

    def closed(*diff_args):
        full = list(datas)
        for i, a in zip(diff_idx, diff_args):
            full[i] = a
        return opdef.fn(*full, **kwargs)

    def closed_norm(*diff_args):
        r = closed(*diff_args)
        return tuple(r) if isinstance(r, list) else r  # keep vjp pytree a tuple

    with _Scope(False, None):  # do not re-record inside vjp tracing
        out, vjp_fn = jax.vjp(closed_norm, *[datas[i] for i in diff_idx])
    multi = isinstance(out, tuple)
    outs = list(out) if multi else [out]
    avals = [(o.shape, o.dtype) for o in outs]
    node = _Node(vjp_fn, [(parents[i], i) for i in diff_idx], avals, opdef.name,
                 out_is_tuple=multi, closed=closed_norm,
                 primals=[inputs[i] for i in diff_idx])
    # parents entries: (parent_ag, input_pos)
    wrapped = []
    like = next((x for x in inputs if isinstance(x, NDArray)), None)
    for i, o in enumerate(outs):
        w = NDArray(o, ctx=like._ctx if like is not None else None)
        w._ag_node = (node, i)
        wrapped.append(w)
    return tuple(wrapped) if multi else wrapped[0]


def backward(heads: Sequence, head_grads: Optional[Sequence] = None,
             retain_graph: bool = False, train_mode: bool = True) -> None:
    """Compute gradients of heads w.r.t. all attached variables, depositing
    into ``.grad`` per each variable's grad_req ('write' or 'add')."""
    _run_backward(heads, head_grads, retain_graph, create_graph=False,
                  deposit=True, train=train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode: bool = True) -> List:
    """Return gradients of heads w.r.t. ``variables`` (no .grad deposit).

    With ``create_graph=True`` the backward pass itself is recorded, enabling
    higher-order gradients.
    """
    if retain_graph is None:
        retain_graph = create_graph
    var_list = list(variables) if isinstance(variables, (list, tuple)) else [variables]
    grads = _run_backward(heads, head_grads, retain_graph, create_graph, deposit=False,
                          wanted=var_list, train=train_mode)
    return grads if isinstance(variables, (list, tuple)) else grads[0]


def _run_backward(heads, head_grads, retain_graph, create_graph, deposit,
                  wanted=None, train=True):
    from .ndarray.ndarray import NDArray

    heads = list(heads) if isinstance(heads, (list, tuple)) else [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    head_grads = [g._data if isinstance(g, NDArray) else g for g in head_grads]

    # Seed cotangents
    cotangents = {}  # id(node) -> list per output
    node_by_id = {}

    def _acc(a, b):
        """a + b, lifting to NDArray when either side is one (create_graph
        threads NDArray cotangents through the walk)."""
        if isinstance(a, NDArray) or isinstance(b, NDArray):
            a = a if isinstance(a, NDArray) else NDArrayCls()(jnp.asarray(a))
            b = b if isinstance(b, NDArray) else NDArrayCls()(jnp.asarray(b))
        return a + b

    def seed(node, idx, ct):
        lst = cotangents.setdefault(id(node), [None] * len(getattr(node, "out_avals", [None])))
        if isinstance(node, _VarNode):
            lst = cotangents.setdefault(id(node), [None])
        if lst[idx] is None:
            lst[idx] = ct
        else:
            lst[idx] = _acc(lst[idx], ct)
        node_by_id[id(node)] = node

    for h, hg in zip(heads, head_grads):
        if h._ag_node is None:
            continue
        node, idx = h._ag_node
        ct = hg if hg is not None else jnp.ones(h.shape, h.dtype)
        seed(node, idx, ct)

    if not node_by_id:
        raise ValueError("cannot differentiate: no recorded computation reaches the heads "
                         "(did you call attach_grad() and compute inside autograd.record()?)")

    # Topological order via iterative DFS (tapes can be 10k+ ops deep — e.g.
    # unrolled RNNs — so no recursion).
    visited, order = set(), []
    stack = []
    for h in heads:
        if h._ag_node is not None and not isinstance(h._ag_node[0], _VarNode):
            stack.append((h._ag_node[0], False))
    while stack:
        node, expanded = stack.pop()
        if isinstance(node, _VarNode):
            continue
        if expanded:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for parent_entry in node.parents:
            pag, _pos = parent_entry
            if pag is not None and id(pag[0]) not in visited:
                stack.append((pag[0], False))

    var_grads = {}  # id(varnode) -> cotangent

    def deposit_var(vnode, ct):
        key = id(vnode)
        var_grads[key] = ct if key not in var_grads else _acc(var_grads[key], ct)
        node_by_id[key] = vnode

    # seed direct-variable heads
    for h, hg in zip(heads, head_grads):
        if h._ag_node is not None and isinstance(h._ag_node[0], _VarNode):
            deposit_var(h._ag_node[0], hg if hg is not None else jnp.ones(h.shape, h.dtype))

    if create_graph:
        from .ndarray.ndarray import invoke_fn

        def _lift(x):
            return x if isinstance(x, NDArray) else NDArrayCls()(jnp.asarray(x))

    # NOTE: `record(train)` not `record(train_mode)` — the latter is the
    # module-level context-manager function (always truthy), which silently
    # forced training semantics into replayed backward forwards
    rec_scope = record(train) if create_graph else _Scope(False, None)
    with rec_scope:
        for node in reversed(order):
            cts = cotangents.get(id(node))
            if cts is None:
                continue
            full_cts = []
            for i, aval in enumerate(node.out_avals):
                c = cts[i] if i < len(cts) and cts[i] is not None else jnp.zeros(aval[0], aval[1])
                full_cts.append(c)
            if create_graph:
                # Re-derive the vjp from (primals, cotangents) as a RECORDED
                # op: the new tape node's parents include the primals, so a
                # second backward reaches d(grad)/d(primal). The stored
                # vjp_fn cannot do this — it closes over residuals.
                if node.closed is None:
                    raise NotImplementedError(
                        f"create_graph=True through a custom autograd."
                        f"Function node ({node.name}) is not supported")
                prim = [_lift(p) for p in node.primals]
                ctnd = [_lift(c) for c in full_cts]
                k = len(prim)

                def vfn(*args, _n=node, _k=k):
                    ps, cs = args[:_k], args[_k:]
                    arg2 = tuple(cs) if _n.out_is_tuple else cs[0]
                    _, vjp = jax.vjp(_n.closed, *ps)
                    return vjp(arg2)

                in_cts = invoke_fn(vfn, prim + ctnd)
                if not isinstance(in_cts, tuple):
                    in_cts = (in_cts,)
            else:
                arg = tuple(full_cts) if node.out_is_tuple else full_cts[0]
                in_cts = node.vjp_fn(arg)
            for (parent_entry, _inpos), ict in zip(node.parents, in_cts):
                if parent_entry is None or ict is None:
                    continue
                pnode, pidx = parent_entry
                if isinstance(pnode, _VarNode):
                    deposit_var(pnode, ict)
                else:
                    seed(pnode, pidx, ict)
            if not retain_graph:
                # release everything that pins activations: vjp residuals
                # AND the create_graph bookkeeping (closed closes over all
                # input buffers; primals strongly ref the input NDArrays)
                node.vjp_fn = None
                node.closed = None
                node.primals = None

    if deposit:
        for key, ct in var_grads.items():
            vnode = node_by_id[key]
            arr = vnode.ref()
            if arr is None or arr._grad_req == "null":
                continue
            if isinstance(ct, NDArray):
                ct = ct._data
            if arr._grad_req == "add":
                arr._grad._set_data(arr._grad._data + ct)
            else:
                arr._grad._set_data(jnp.asarray(ct, arr.dtype))
        return None

    out = []
    for v in wanted or []:
        if v._ag_node is None or not isinstance(v._ag_node[0], _VarNode):
            raise ValueError("grad() target was not attached via attach_grad()")
        ct = var_grads.get(id(v._ag_node[0]))
        if ct is None:
            ct = jnp.zeros(v.shape, v.dtype)
        # NDArray cotangents (create_graph=True) keep their tape link so a
        # second backward() can differentiate through the first
        g = ct if isinstance(ct, NDArray) else NDArrayCls()(ct)
        out.append(g)
    return out


def NDArrayCls():
    from .ndarray.ndarray import NDArray

    return NDArray


class Function:
    """User-defined differentiable function (reference autograd.Function:
    custom forward with a hand-written backward, recorded as ONE tape node).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` (returning one gradient per NDArray
    input, in input order). ``save_for_backward(*arrays)`` stashes tensors
    on ``self.saved_tensors`` for the backward.
    """

    def __init__(self):
        self.saved_tensors = ()

    def save_for_backward(self, *args):
        self.saved_tensors = args

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray

        rec = is_recording()
        with _Scope(False, None):  # user forward runs unrecorded
            out = self.forward(*inputs)
        if not rec:
            return out
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]
        nd_pos = [i for i, x in enumerate(inputs) if isinstance(x, NDArray)]
        avals = [(o.shape, o.dtype) for o in outs]

        def vjp_fn(arg):
            cts = arg if multi else (arg,)
            with _Scope(False, None):
                grads = self.backward(*[NDArrayCls()(jnp.asarray(c))
                                        for c in cts])
            grads = (list(grads) if isinstance(grads, (tuple, list))
                     else [grads])
            if len(grads) != len(nd_pos):
                raise ValueError(
                    f"{type(self).__name__}.backward returned {len(grads)} "
                    f"gradients for {len(nd_pos)} array inputs")
            return tuple(g._data if isinstance(g, NDArray) else g
                         for g in grads)

        node = _Node(vjp_fn, [(inputs[i]._ag_node, i) for i in nd_pos],
                     avals, type(self).__name__, out_is_tuple=multi)
        wrapped = []
        for i, o in enumerate(outs):
            w = NDArrayCls()(o._data if isinstance(o, NDArray) else o)
            w._ag_node = (node, i)
            wrapped.append(w)
        return tuple(wrapped) if multi else wrapped[0]
