"""``mxnet_tpu.analysis`` — graph & trace static analysis (pre-flight lint).

The reference front-loads graph validation in C++ NNVM passes
(FInferShape/FInferType reject bad graphs before the Executor runs); this
package is the TPU-native counterpart, catching both late crashes and
*silent* perf bugs before any XLA compilation:

- :class:`GraphLinter` — pass-based lint over Symbol graphs (shape/dtype
  pre-flight with per-node attribution, dead nodes, duplicate names,
  non-differentiable ops on the gradient path, numeric idioms, fan-out);
- :class:`TraceLinter` — jit-trace hygiene for HybridBlocks (retrace
  churn, concretization leaks, weak-dtype promotion);
- :class:`ShardingLinter` — PartitionSpec rule tables vs the mesh
  (unknown axes, indivisible dims, accidentally replicated large params);
- repo self-lint (``tools/lint_repo.py``) — framework invariants over the
  source tree itself;
- concurrency lint (``python -m mxnet_tpu.analysis concurrency``) —
  lock-order cycles, blocking-under-lock, CV/thread discipline, and the
  wire-protocol registry checks over the threaded serve/PS planes (the
  runtime twin is ``mxnet_tpu.tsan``).

User surfaces: ``Symbol.lint(...)``, ``bind(..., lint="warn"|"error")``,
``python -m mxnet_tpu.analysis graph.json``. See docs/ANALYSIS.md.
"""
from . import concurrency  # noqa: F401  (the lock/protocol linter)
from . import dataplane  # noqa: F401  (the copy/sync/allocation linter)
from .findings import Finding, GraphAnalysisError, Report, Severity  # noqa: F401
from .graph import GraphView, NodeInfo  # noqa: F401
from .graph_passes import GraphLinter, LintContext, graph_pass, list_passes  # noqa: F401
from .sharding import ShardingLinter  # noqa: F401
from .trace import TraceLinter  # noqa: F401

__all__ = [
    "Finding", "GraphAnalysisError", "Report", "Severity",
    "GraphView", "NodeInfo",
    "GraphLinter", "LintContext", "graph_pass", "list_passes",
    "ShardingLinter", "TraceLinter", "concurrency", "dataplane",
]
