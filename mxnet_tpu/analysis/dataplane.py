"""DataplaneLinter — static copy/sync/allocation lint for the hot paths.

ROADMAP item 4 (zero-copy wire + event-loop data plane) needs a machine-
checked definition of "zero-copy" and "non-blocking hot path" before the
rewrite can land safely; the repo's own history (PRs 3, 4, 10, 13, 15)
shows the same data-plane bug classes fixed by hand repeatedly: blocking
host syncs on request paths, per-frame buffer copies, unbounded
server-side caches, and leaked sockets/threads on exception paths. This
pass is the ``analysis/`` family member that turns those into contracts,
the way ``concurrency.py`` did for locks. The runtime twin is
``mxnet_tpu.copytrack`` (``MXNET_COPYTRACK=1``), which *measures* the
copies and syncs this pass can only prove reachable.

Rules (docs/ANALYSIS.md "Data-plane lint" has the catalog):

- ``pickle-on-wire`` (error) — ``pickle``/``marshal``/``.tojson()`` on a
  hot-reachable or wire-framing function: array payloads must transit
  the ``_pack_arrays``/memoryview framing, never an object serializer.
- ``redundant-buffer-copy`` (warning) — ``bytes``-accumulating ``+=``,
  per-frame ``b"".join`` inside a loop, ``.tobytes()`` of an array, or
  slicing received ``bytes`` where a ``memoryview`` suffices, on a
  send/recv or hot-reachable function — the scatter-gather
  preconditions for item 4.
- ``host-sync-on-hot-path`` (warning) — ``asnumpy``/``device_get``/
  ``block_until_ready``/``copy_to_host_async`` reachable from a declared
  hot root (same-class interprocedural propagation, the PR-12
  blocking-call idiom). ``float(arr)``/``np.asarray(jax_array)``
  coercions are type-ambiguous statically; the runtime twin counts
  those. Waived syncs stay inventoried at info severity.
- ``unbounded-collection-growth`` (warning) — a dict/list/set attribute
  initialized in ``__init__`` and mutated inside a handler method or
  loop body, with no eviction/cap/rebind anywhere in the class (the
  released-round-cache / hot-key-table bug class).
- ``resource-lifetime`` (warning) — a locally acquired socket/file/
  thread that is never closed/joined and never handed off (returned,
  stored, passed on): the exception path leaks it.
- ``env-registry-drift`` (warning) — every ``MXNET_*`` environ read must
  have a ``runtime._ENV_REGISTRY`` row and every ``MXNET_*`` registry
  row must have a read (bidirectional; catches doc rot mechanically).

Hot roots (class, method) — the request/step paths everything above is
computed relative to::

    InferenceEngine.infer        serve/engine.py   (bucketed execute)
    DynamicBatcher._loop/_assemble/_execute   serve/batcher.py
    ServeServer._handle_loop/_handle_one      serve/server.py
    PSServer._handle_loop/_handle_one         kvstore/ps_server.py
    Router.infer                 serve/fleet.py    (failover route)
    BaseModule.fit               module/base_module.py (step body)

Waive a deliberate site with ``# lint: disable=<rule-id>`` on the
offending line (justify nearby); waived findings are reported at info
severity with ``details={"waived": True}`` but never fail the lint.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding, Report, Severity
from .repo_lint import _suppressed

__all__ = ["RULES", "HOT_ROOTS", "lint_source", "lint_paths",
           "check_env_registry", "collect_env_reads", "unwaived", "main"]

RULES = {
    "pickle-on-wire":
        "object serializer (pickle/marshal/tojson) on a wire or "
        "hot-reachable path — array bytes must use the array framing",
    "redundant-buffer-copy":
        "avoidable buffer copy on a send/recv path (bytes +=, per-frame "
        "join, .tobytes(), slicing received bytes)",
    "host-sync-on-hot-path":
        "device->host sync (asnumpy/device_get/block_until_ready) "
        "reachable from a declared hot root",
    "unbounded-collection-growth":
        "collection attribute grows in a handler/loop body with no "
        "eviction or cap in the class",
    "resource-lifetime":
        "socket/file/thread acquired but never closed/joined on any "
        "path and never handed off",
    "env-registry-drift":
        "MXNET_* environ read without a runtime._ENV_REGISTRY row, or "
        "a registry row no code reads",
}

# (class name, method name) pairs the reachability analysis seeds from.
HOT_ROOTS: Set[Tuple[str, str]] = {
    ("InferenceEngine", "infer"),
    ("DynamicBatcher", "_loop"),
    ("DynamicBatcher", "_assemble"),
    ("DynamicBatcher", "_execute"),
    ("ServeServer", "_handle_loop"),
    ("ServeServer", "_handle_one"),
    ("FleetServer", "_handle_one"),
    ("PSServer", "_handle_loop"),
    ("PSServer", "_handle_one"),
    ("Router", "infer"),
    ("DecodeScheduler", "step"),
    ("BaseModule", "fit"),
}

# device->host materialization points (rule 3)
_SYNC_ATTRS = {"asnumpy", "device_get", "block_until_ready",
               "copy_to_host_async"}
# object serializers (rule 1)
_PICKLE_MODULES = {"pickle", "cPickle", "marshal"}
_PICKLE_FUNCS = {"dumps", "loads", "dump", "load"}
# eviction evidence on a collection attribute (rule 4)
_EVICT_ATTRS = {"pop", "popitem", "popleft", "clear", "remove", "evict",
                "discard"}
# resource constructors (rule 5): qualified-name suffix -> release verbs
_RESOURCE_CTORS = {
    "socket.socket": ("close", "shutdown", "detach"),
    "socket.create_connection": ("close", "shutdown", "detach"),
    "open": ("close",),
    "threading.Thread": ("join",),
    "Thread": ("join",),
}
# env-read callees (rule 6)
_ENV_READ_FUNCS = {"get_env", "getenv", "env_float", "env_int",
                   "env_str", "env_bool"}
_ENV_NAME_RE = re.compile(r"^MXNET_[A-Z0-9][A-Z0-9_]*$")


def _dotted(expr: ast.AST) -> str:
    """Dotted best-effort name of an attribute chain ('os.environ')."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    return ".".join(reversed(parts))


def _call_name(node: ast.Call) -> str:
    """Dotted best-effort name of a call target ('socket.socket',
    'self._pack', 'open')."""
    return _dotted(node.func)


def _is_self_call(node: ast.Call) -> Optional[str]:
    f = node.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id == "self"):
        return f.attr
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ModuleLinter:
    def __init__(self, src: str, filename: str = "<string>"):
        self.src = src
        self.filename = filename
        self.lines = src.splitlines()
        self.findings: List[Finding] = []
        self.env_reads: List[Tuple[str, int]] = []  # (var name, line)
        try:
            self.tree: Optional[ast.AST] = ast.parse(src)
        except SyntaxError as e:
            self.tree = None
            self.findings.append(Finding(
                "syntax-error", Severity.ERROR, str(e),
                location=f"{filename}:{e.lineno or 0}"))

    # -- emit helpers ---------------------------------------------------
    def emit(self, rule: str, severity: str, msg: str, line: int,
             fix: str, end_line: Optional[int] = None, **details) -> None:
        for ln in range(line, (end_line or line) + 1):
            if _suppressed(self.lines, ln, rule):
                self.emit_waived(rule, line)
                return
        self.findings.append(Finding(
            rule, severity, msg, fix_hint=fix,
            location=f"{self.filename}:{line}", details=details or {}))

    def emit_waived(self, rule: str, line: int) -> None:
        self.findings.append(Finding(
            rule, Severity.INFO, "waived in source (lint: disable)",
            location=f"{self.filename}:{line}", details={"waived": True}))

    # -- analysis -------------------------------------------------------
    def run(self) -> None:
        if self.tree is None:
            return
        self._collect_env_reads(self.tree)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._lint_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._lint_resources(node)
        # module-level wire helpers (the framing functions live outside
        # classes): buffer + serializer rules apply there too
        for node in (self.tree.body if isinstance(self.tree, ast.Module)
                     else []):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and self._is_wire_fn(node):
                self._lint_buffers(node, f"{node.name}()")
                self._lint_serializers(node, f"{node.name}()")

    # -- hot-root reachability ------------------------------------------
    def _lint_class(self, cls: ast.ClassDef) -> None:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # seed + same-class fixpoint: a method called (transitively) from
        # a hot root is hot; remember which root it derives from
        hot: Dict[str, str] = {
            name: f"{cls.name}.{name}" for name in methods
            if (cls.name, name) in HOT_ROOTS}
        changed = True
        while changed:
            changed = False
            for name, fn in methods.items():
                if name not in hot:
                    continue
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    callee = _is_self_call(call)
                    if callee and callee in methods and callee not in hot:
                        hot[callee] = hot[name]
                        changed = True
        for name, fn in methods.items():
            ctx = f"{cls.name}.{name}"
            if name in hot:
                self._lint_syncs(fn, ctx, root=hot[name])
                self._lint_buffers(fn, ctx)
                self._lint_serializers(fn, ctx)
            elif self._is_wire_fn(fn):
                self._lint_buffers(fn, ctx)
                self._lint_serializers(fn, ctx)
        self._lint_growth(cls, methods, set(hot))

    def _is_wire_fn(self, fn: ast.AST) -> bool:
        """A function is on the wire path if it is a framing helper by
        name or touches a socket send/recv itself."""
        if fn.name.startswith(("_pack", "_unpack", "_send", "_recv",
                               "_reply")):
            return True
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr in (
                    "sendall", "sendmsg", "recv", "recv_into"):
                return True
        return False

    # -- rule 3: host syncs ---------------------------------------------
    def _lint_syncs(self, fn: ast.AST, ctx: str, root: str) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_ATTRS:
                self.emit(
                    "host-sync-on-hot-path", Severity.WARNING,
                    f"{ctx}: .{node.func.attr}() is a device->host sync "
                    f"reachable from hot root {root}",
                    node.lineno,
                    "keep results device-resident (or waive: intentional "
                    "syncs stay inventoried at info severity)",
                    end_line=getattr(node, "end_lineno", None),
                    root=root, sync=node.func.attr)

    # -- rule 1: object serializers on wire paths -----------------------
    def _lint_serializers(self, fn: ast.AST, ctx: str) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            mod, _, leaf = name.rpartition(".")
            hit = None
            if mod.split(".")[-1] in _PICKLE_MODULES \
                    and leaf in _PICKLE_FUNCS:
                hit = name
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "tojson":
                hit = ".tojson()"
            if hit:
                self.emit(
                    "pickle-on-wire", Severity.ERROR,
                    f"{ctx}: {hit} on a wire/hot path — array payloads "
                    "must use the _pack_arrays/memoryview framing",
                    node.lineno,
                    "frame arrays with _pack_arrays; reserve object "
                    "serializers for small non-array metadata (waive with "
                    "a justification if so)",
                    end_line=getattr(node, "end_lineno", None),
                    call=hit)

    # -- rule 2: redundant buffer copies --------------------------------
    def _lint_buffers(self, fn: ast.AST, ctx: str) -> None:
        bytes_locals: Set[str] = set()
        recv_locals: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                v = node.value
                if isinstance(v, ast.Constant) and isinstance(v.value, bytes):
                    bytes_locals.add(tgt)
                elif isinstance(v, ast.Call):
                    vname = _call_name(v)
                    leaf = vname.rpartition(".")[2]
                    if "recv" in leaf:
                        recv_locals.add(tgt)
                    elif leaf == "memoryview":
                        recv_locals.discard(tgt)

        def visit(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, ast.Add) \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id in bytes_locals:
                self.emit(
                    "redundant-buffer-copy", Severity.WARNING,
                    f"{ctx}: '{node.target.id} +=' reallocates and copies "
                    "the whole accumulated buffer every iteration",
                    node.lineno,
                    "append chunks to a list and b''.join once after the "
                    "loop (or write into a preallocated bytearray)",
                    end_line=getattr(node, "end_lineno", None),
                    kind="bytes-augassign")
            elif isinstance(node, ast.Call):
                f = node.func
                if in_loop and isinstance(f, ast.Attribute) \
                        and f.attr == "join" \
                        and isinstance(f.value, ast.Constant) \
                        and isinstance(f.value.value, bytes):
                    self.emit(
                        "redundant-buffer-copy", Severity.WARNING,
                        f"{ctx}: per-frame b''.join inside a loop copies "
                        "every frame's bytes again",
                        node.lineno,
                        "collect pieces across the loop and join once "
                        "(or hand the piece list to sendmsg)",
                        end_line=getattr(node, "end_lineno", None),
                        kind="join-in-loop")
                if isinstance(f, ast.Attribute) \
                        and f.attr in ("sendall", "send") and node.args \
                        and isinstance(node.args[0], ast.BinOp) \
                        and isinstance(node.args[0].op, ast.Add):
                    self.emit(
                        "redundant-buffer-copy", Severity.WARNING,
                        f"{ctx}: concatenating buffers in the "
                        f"{f.attr}() argument copies the whole message "
                        "first",
                        node.lineno,
                        "hand the parts to sendmsg() (scatter-gather) "
                        "instead of header + body",
                        end_line=getattr(node, "end_lineno", None),
                        kind="concat-before-send")
                if isinstance(f, ast.Attribute) and f.attr == "tobytes":
                    self.emit(
                        "redundant-buffer-copy", Severity.WARNING,
                        f"{ctx}: .tobytes() copies the whole array into a "
                        "fresh bytes object",
                        node.lineno,
                        "pass memoryview(arr) / arr.data to the send path "
                        "(scatter-gather; ROADMAP item 4)",
                        end_line=getattr(node, "end_lineno", None),
                        kind="tobytes")
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in recv_locals \
                    and isinstance(node.slice, ast.Slice):
                # memoryview-wrapped receives are exempt (slicing a
                # memoryview is free); prescan dropped those names
                self.emit(
                    "redundant-buffer-copy", Severity.WARNING,
                    f"{ctx}: slicing received bytes "
                    f"'{node.value.id}[...]' copies the slice",
                    node.lineno,
                    "wrap the receive in memoryview() before slicing",
                    end_line=getattr(node, "end_lineno", None),
                    kind="bytes-slice")
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                # only the loop BODY repeats; the iterable/test is
                # evaluated outside the per-iteration cost
                for field, sub in ast.iter_fields(node):
                    kids = sub if isinstance(sub, list) else [sub]
                    per_iter = in_loop or field in ("body", "orelse")
                    for c in kids:
                        if isinstance(c, ast.AST):
                            visit(c, per_iter)
            else:
                for c in ast.iter_child_nodes(node):
                    visit(c, in_loop)

        for stmt in fn.body:
            visit(stmt, False)

    # -- rule 4: unbounded collection growth ----------------------------
    def _lint_growth(self, cls: ast.ClassDef, methods, hot: Set[str]
                     ) -> None:
        init = methods.get("__init__")
        if init is None:
            return
        grown: Dict[str, int] = {}  # attr -> init line
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            attr = _self_attr(node.targets[0])
            if attr is None:
                continue
            v = node.value
            unbounded = False
            if isinstance(v, (ast.Dict, ast.List, ast.Set)) \
                    and not getattr(v, "keys", None) \
                    and not getattr(v, "elts", None):
                unbounded = True
            elif isinstance(v, ast.Call):
                ctor = _call_name(v).rpartition(".")[2]
                if ctor in ("dict", "list", "set", "OrderedDict",
                            "defaultdict") and not v.args:
                    unbounded = True
                elif ctor == "deque" and not any(
                        kw.arg == "maxlen" for kw in v.keywords):
                    unbounded = True
            if unbounded:
                grown[attr] = node.lineno
        if not grown:
            return
        capped: Set[str] = set()
        for name, fn in methods.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) \
                            and f.attr in _EVICT_ATTRS:
                        a = _self_attr(f.value)
                        if a:
                            capped.add(a)
                    # a length check against the attr is cap awareness
                    if isinstance(f, ast.Name) and f.id == "len" \
                            and node.args:
                        a = _self_attr(node.args[0])
                        if a:
                            capped.add(a)
                if isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript):
                            a = _self_attr(t.value)
                            if a:
                                capped.add(a)
                if name != "__init__" and isinstance(node, ast.Assign):
                    for t in node.targets:
                        a = _self_attr(t)
                        if a:
                            capped.add(a)  # rebind = reset
        handlerish = hot | {n for n in methods
                            if n.startswith(("_handle", "_loop"))
                            or n in ("serve_forever", "run", "_run")}
        for name, fn in methods.items():
            if name == "__init__":
                continue  # construction-time growth is bounded by config
            for node in ast.walk(fn):
                mut_attr = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Subscript):
                    mut_attr = _self_attr(node.targets[0].value)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("append", "add",
                                               "setdefault", "extend",
                                               "appendleft", "update"):
                    mut_attr = _self_attr(node.func.value)
                if mut_attr is None or mut_attr not in grown \
                        or mut_attr in capped:
                    continue
                if name in handlerish or self._in_loop(fn, node):
                    self.emit(
                        "unbounded-collection-growth", Severity.WARNING,
                        f"{cls.name}.{mut_attr} grows in "
                        f"{cls.name}.{name} with no eviction/cap "
                        "anywhere in the class",
                        node.lineno,
                        "cap it (LRU popitem / deque(maxlen=) / periodic "
                        "prune) or rebind it per round",
                        end_line=getattr(node, "end_lineno", None),
                        attr=mut_attr, method=name)
                    capped.add(mut_attr)  # one finding per attribute

    @staticmethod
    def _in_loop(fn: ast.AST, target: ast.AST) -> bool:
        """True if ``target`` sits inside a For/While within ``fn``."""
        found = [False]

        def visit(node, in_loop):
            if node is target:
                found[0] = found[0] or in_loop
                return
            child_loop = in_loop or isinstance(
                node, (ast.For, ast.While, ast.AsyncFor))
            for c in ast.iter_child_nodes(node):
                visit(c, child_loop)

        visit(fn, False)
        return found[0]

    # -- rule 5: resource lifetime --------------------------------------
    def _lint_resources(self, fn: ast.AST) -> None:
        acquired: Dict[str, Tuple[int, str, Tuple[str, ...]]] = {}
        for stmt in ast.walk(fn):
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1 \
                    or not isinstance(stmt.targets[0], ast.Name) \
                    or not isinstance(stmt.value, ast.Call):
                continue
            name = _call_name(stmt.value)
            for ctor, verbs in _RESOURCE_CTORS.items():
                if name == ctor or name.endswith("." + ctor):
                    if ctor in ("threading.Thread", "Thread") and any(
                            kw.arg == "daemon" for kw in
                            stmt.value.keywords):
                        break  # daemon thread: supervised by lifetime
                    acquired[stmt.targets[0].id] = (
                        stmt.lineno, ctor, verbs)
                    break
        if not acquired:
            return
        released: Set[str] = set()
        escaped: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in acquired:
                    var = f.value.id
                    if f.attr in acquired[var][2]:
                        released.add(var)
                # passed as an argument -> ownership handed off
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name) \
                                and sub.id in acquired:
                            escaped.add(sub.id)
            elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                val = getattr(node, "value", None)
                if val is not None:
                    for sub in ast.walk(val):
                        if isinstance(sub, ast.Name) \
                                and sub.id in acquired:
                            escaped.add(sub.id)
            elif isinstance(node, ast.Assign):
                # stored on self/a collection -> tracked elsewhere;
                # `t.daemon = True` -> supervised
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name) and sub.id in acquired:
                        escaped.add(sub.id)
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in acquired \
                            and t.attr == "daemon":
                        released.add(t.value.id)
        for var, (line, ctor, verbs) in acquired.items():
            if var in released or var in escaped:
                continue
            self.emit(
                "resource-lifetime", Severity.WARNING,
                f"{fn.name}(): '{var}' ({ctor}) is acquired but never "
                f"{'/'.join(verbs)}ed and never handed off — the "
                "exception path leaks it",
                line,
                "use a with-statement or try/finally "
                f"{var}.{verbs[0]}() (or store it on a supervisor that "
                "owns shutdown)",
                var=var, ctor=ctor)

    # -- rule 6 support: env reads --------------------------------------
    def _collect_env_reads(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            names: List[Tuple[str, int]] = []
            if isinstance(node, ast.Call):
                callee = _call_name(node)
                leaf = callee.rpartition(".")[2].lstrip("_")
                env_call = (leaf in _ENV_READ_FUNCS
                            or ("environ" in callee
                                and leaf in ("get", "setdefault", "pop")))
                if env_call:
                    for arg in node.args[:1]:
                        if isinstance(arg, ast.Constant) \
                                and isinstance(arg.value, str):
                            val = arg.value
                            if leaf == "get_env" \
                                    and not val.startswith("MXNET_") \
                                    and re.match(r"^[A-Z0-9_]+$", val):
                                # base.get_env auto-prefixes short names
                                val = "MXNET_" + val
                            names.append((val, node.lineno))
            elif isinstance(node, ast.Subscript):
                base = _dotted(node.value)
                if "environ" in base and isinstance(node.slice,
                                                    ast.Constant) \
                        and isinstance(node.slice.value, str):
                    names.append((node.slice.value, node.lineno))
            elif isinstance(node, ast.Compare) \
                    and isinstance(node.left, ast.Constant) \
                    and isinstance(node.left.value, str) \
                    and any(isinstance(op, (ast.In, ast.NotIn))
                            for op in node.ops):
                names.append((node.left.value, node.lineno))
            for name, line in names:
                if _ENV_NAME_RE.match(name):
                    self.env_reads.append((name, line))


# ---------------------------------------------------------------------------
# rule 6: bidirectional env-registry drift (repo-level)
# ---------------------------------------------------------------------------

def collect_env_reads(sources: Dict[str, str]
                      ) -> Dict[str, List[Tuple[str, int]]]:
    """``MXNET_*`` env reads per name: ``{name: [(file, line), ...]}``."""
    reads: Dict[str, List[Tuple[str, int]]] = {}
    for fname, src in sources.items():
        m = _ModuleLinter(src, fname)
        if m.tree is None:
            continue
        m._collect_env_reads(m.tree)
        for name, line in m.env_reads:
            reads.setdefault(name, []).append((fname, line))
    return reads


def check_env_registry(sources: Dict[str, str],
                       registry: Optional[Iterable[str]] = None
                       ) -> List[Finding]:
    """Bidirectional drift between ``MXNET_*`` reads in ``sources`` and
    the env registry (``runtime._ENV_REGISTRY`` keys by default; pass an
    explicit iterable in tests). Only runs the dead-row direction when a
    scanned file defines ``_ENV_REGISTRY`` (so single-file lints don't
    declare the whole registry dead)."""
    if registry is None:
        from .. import runtime

        registry = runtime._ENV_REGISTRY.keys()
    full = set(registry)
    reg = {k for k in full if k.startswith("MXNET_")}
    reads = collect_env_reads(sources)
    out: List[Finding] = []
    for name in sorted(set(reads) - reg):
        # base.get_env("DMLC_X") falls back to MXNET_DMLC_X, so a row for
        # the unprefixed name documents the prefixed alias too.
        if name[len("MXNET_"):] in full:
            continue
        for fname, line in reads[name]:
            lines = sources[fname].splitlines()
            if _suppressed(lines, line, "env-registry-drift"):
                out.append(Finding(
                    "env-registry-drift", Severity.INFO,
                    "waived in source (lint: disable)",
                    location=f"{fname}:{line}",
                    details={"waived": True}))
                continue
            out.append(Finding(
                "env-registry-drift", Severity.WARNING,
                f"{name} is read here but has no runtime._ENV_REGISTRY "
                "row (undocumented knob)",
                location=f"{fname}:{line}",
                fix_hint="add a registry row with the default and a "
                         "one-line description (env_list() is the docs "
                         "table)",
                details={"name": name, "direction": "undocumented"}))
    registry_files = [f for f, s in sources.items()
                      if "_ENV_REGISTRY" in s and "runtime" in
                      os.path.basename(f)]
    if registry_files:
        regfile = registry_files[0]
        reglines = sources[regfile].splitlines()
        for name in sorted(reg - set(reads)):
            line = next((i + 1 for i, ln in enumerate(reglines)
                         if f'"{name}"' in ln), 1)
            if _suppressed(reglines, line, "env-registry-drift"):
                out.append(Finding(
                    "env-registry-drift", Severity.INFO,
                    "waived in source (lint: disable)",
                    location=f"{regfile}:{line}",
                    details={"waived": True}))
                continue
            out.append(Finding(
                "env-registry-drift", Severity.WARNING,
                f"registry row {name} has no read anywhere in the "
                "scanned tree (dead knob)",
                location=f"{regfile}:{line}",
                fix_hint="prune the row, or wire the knob back up",
                details={"name": name, "direction": "dead-row"}))
    return out


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def unwaived(report) -> List[Finding]:
    return [f for f in report if not f.details.get("waived")]


def lint_source(src: str, filename: str = "<string>") -> List[Finding]:
    """Single-file lint (rule unit tests): rules 1-5. The registry-drift
    rule needs the whole tree — see :func:`lint_paths` /
    :func:`check_env_registry`."""
    m = _ModuleLinter(src, filename)
    m.run()
    return m.findings


def lint_paths(paths: Iterable[str], exclude: Iterable[str] = ()
               ) -> Report:
    """Repo lint: rules 1-5 per file plus the bidirectional env-registry
    drift check over everything scanned."""
    report = Report()
    exclude = tuple(exclude)
    sources: Dict[str, str] = {}
    files: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            files.append(path)
        else:
            for root, _dirs, names in os.walk(path):
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(root, n))
    for f in sorted(files):
        if any(x in f for x in exclude):
            continue
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        sources[f] = src
        m = _ModuleLinter(src, f)
        m.run()
        report.extend(m.findings)
    report.extend(check_env_registry(sources))
    return report


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis dataplane",
        description="Data-plane lint: hot-path copy/sync/allocation "
                    "rules, resource lifetime, env-registry drift. The "
                    "runtime twin is MXNET_COPYTRACK=1.")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: mxnet_tpu)")
    ap.add_argument("--exclude", action="append", default=[],
                    help="path substring to skip")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog")
    ap.add_argument("--no-waived", action="store_true",
                    help="hide waived findings from the report")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}: {desc}")
        return 0

    report = lint_paths(args.paths or ["mxnet_tpu"], exclude=args.exclude)
    shown = Report(unwaived(report)) if args.no_waived else report
    print(shown.to_json() if args.json else shown.format())
    bad = unwaived(report)
    if bad:
        print(f"\n{len(bad)} unwaived finding(s) "
              f"({len(report) - len(bad)} waived)")
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
