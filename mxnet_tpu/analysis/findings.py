"""Finding/Report datatypes shared by every linter in ``mxnet_tpu.analysis``.

The reference front-loads graph validation inside NNVM C++ passes and
surfaces failures as ``MXNetError`` strings; here every analyzer (graph,
trace, sharding, repo self-lint) emits the same structured ``Finding`` so
user surfaces (``Symbol.lint``, ``bind(lint=...)``, the CLI) can filter by
severity/rule and render uniformly.
"""
from __future__ import annotations

import json as _json
from dataclasses import dataclass, field
from typing import Iterable, Iterator, List, Optional

from ..base import GraphAnalysisError  # noqa: F401  (canonical re-export)

__all__ = ["Severity", "Finding", "Report", "GraphAnalysisError"]


class Severity:
    """Severity levels, ordered. Plain strings so findings json-serialize."""

    ERROR = "error"      # graph will crash or silently compute the wrong thing
    WARNING = "warning"  # very likely a bug or a serious perf hazard
    INFO = "info"        # worth knowing; often intentional

    ORDER = (ERROR, WARNING, INFO)

    @classmethod
    def rank(cls, sev: str) -> int:
        try:
            return cls.ORDER.index(sev)
        except ValueError:
            return len(cls.ORDER)


@dataclass
class Finding:
    """One lint result.

    ``node`` is the graph-node (or parameter/file) the finding is anchored
    to; ``fix_hint`` is a one-line actionable suggestion.
    """

    rule_id: str
    severity: str
    message: str
    node: Optional[str] = None
    op: Optional[str] = None
    fix_hint: Optional[str] = None
    location: Optional[str] = None  # file:line for source-level linters
    details: dict = field(default_factory=dict)

    def format(self) -> str:
        where = self.node or self.location or ""
        head = f"[{self.severity}] {self.rule_id}"
        if where:
            head += f" @ {where}"
        if self.op:
            head += f" ({self.op})"
        out = f"{head}: {self.message}"
        if self.fix_hint:
            out += f"\n    hint: {self.fix_hint}"
        return out

    def to_dict(self) -> dict:
        d = {"rule_id": self.rule_id, "severity": self.severity,
             "message": self.message}
        for k in ("node", "op", "fix_hint", "location"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        if self.details:
            d["details"] = self.details
        return d


class Report:
    """Ordered collection of findings with severity helpers."""

    def __init__(self, findings: Optional[Iterable[Finding]] = None):
        self.findings: List[Finding] = list(findings or [])

    # -- collection protocol -------------------------------------------
    def add(self, finding: Finding) -> "Report":
        self.findings.append(finding)
        return self

    def extend(self, findings: Iterable[Finding]) -> "Report":
        self.findings.extend(findings)
        return self

    def __iter__(self) -> Iterator[Finding]:
        return iter(self.findings)

    def __len__(self) -> int:
        return len(self.findings)

    def __bool__(self) -> bool:
        return bool(self.findings)

    # -- filtering ------------------------------------------------------
    def by_severity(self, severity: str) -> "Report":
        return Report(f for f in self.findings if f.severity == severity)

    def by_rule(self, rule_id: str) -> "Report":
        return Report(f for f in self.findings if f.rule_id == rule_id)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == Severity.WARNING]

    @property
    def has_errors(self) -> bool:
        return any(f.severity == Severity.ERROR for f in self.findings)

    # -- rendering ------------------------------------------------------
    def sorted(self) -> "Report":
        return Report(sorted(self.findings,
                             key=lambda f: Severity.rank(f.severity)))

    def summary(self) -> str:
        n_err = len(self.errors)
        n_warn = len(self.warnings)
        n_info = len(self.findings) - n_err - n_warn
        return f"{n_err} error(s), {n_warn} warning(s), {n_info} info"

    def format(self) -> str:
        if not self.findings:
            return "clean: no findings"
        lines = [f.format() for f in self.sorted()]
        lines.append(self.summary())
        return "\n".join(lines)

    def to_json(self) -> str:
        return _json.dumps({"findings": [f.to_dict() for f in self.findings],
                            "summary": self.summary()}, indent=2)

    def raise_if_errors(self) -> "Report":
        """Raise :class:`GraphAnalysisError` if any error-severity finding."""
        errs = self.errors
        if errs:
            first = errs[0]
            msg = "\n".join(f.format() for f in errs)
            raise GraphAnalysisError(
                f"graph lint failed with {len(errs)} error(s):\n{msg}",
                node=first.node, op=first.op, rule_id=first.rule_id,
                findings=errs)
        return self

    def __repr__(self):
        return f"<Report {self.summary()}>"
