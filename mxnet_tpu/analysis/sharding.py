"""ShardingLinter — static checks of PartitionSpec rule tables vs a mesh.

``parallel.ShardingRules`` deliberately *prunes* silently (axes missing
from the mesh or not dividing a dim collapse to replicated) so one rule
table serves every mesh. That tolerance hides real deployment bugs: a
typo'd axis name replicates a 30B-param matrix on every chip without a
peep. This linter surfaces exactly what pruning dropped and which large
parameters ended up fully replicated.

Rule ids:

- ``spec-rank-mismatch``     spec has more axes than the param has dims (error)
- ``unknown-mesh-axis``      spec names an axis the mesh doesn't have (warning)
- ``indivisible-dim``        dim size not divisible by the mesh axis (warning)
- ``replicated-large-param`` big param left fully replicated on a >1-device
                             mesh (warning)
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .findings import Finding, Report, Severity

__all__ = ["ShardingLinter"]


class ShardingLinter:
    def __init__(self, mesh, rules, large_param_threshold: int = 1 << 20):
        self.mesh = mesh
        self.rules = rules
        self.large_param_threshold = int(large_param_threshold)

    def _raw_spec(self, name: str):
        for pat, spec in self.rules.rules:
            if pat.search(name):
                return spec
        return self.rules.default

    def lint(self, named_shapes: Dict[str, tuple]) -> Report:
        from jax.sharding import PartitionSpec as P  # noqa: F401

        report = Report()
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        mesh_ndev = int(np.prod(self.mesh.devices.shape))
        for name, shape in named_shapes.items():
            shape = tuple(shape)
            spec = self._raw_spec(name)
            if len(spec) > len(shape):
                report.add(Finding(
                    "spec-rank-mismatch", Severity.ERROR,
                    f"param {name!r} has rank {len(shape)} {shape} but its "
                    f"rule spec {spec} names {len(spec)} dims",
                    node=name,
                    fix_hint="trim the PartitionSpec or fix the rule regex "
                             "so it matches the intended params"))
                continue
            any_sharded = False
            for i, ax in enumerate(spec):
                if ax is None:
                    continue
                axes = ax if isinstance(ax, tuple) else (ax,)
                missing = [a for a in axes if a not in sizes]
                if missing:
                    report.add(Finding(
                        "unknown-mesh-axis", Severity.WARNING,
                        f"param {name!r} dim {i} spec {ax!r}: mesh has no "
                        f"axis {missing} (mesh axes: {sorted(sizes)}); the "
                        "dim silently replicates",
                        node=name,
                        fix_hint="add the axis to make_mesh(...) or drop it "
                                 "from the rule"))
                    continue
                total = 1
                for a in axes:
                    total *= sizes[a]
                if total > 1 and shape[i] % total != 0:
                    report.add(Finding(
                        "indivisible-dim", Severity.WARNING,
                        f"param {name!r} dim {i} (size {shape[i]}) is not "
                        f"divisible by mesh axis {ax!r} (size {total}); the "
                        "dim silently replicates",
                        node=name,
                        fix_hint="pad the dim to a multiple of the axis "
                                 "size, or reshape the mesh"))
                    continue
                if total > 1:
                    any_sharded = True
            n_elem = int(np.prod(shape)) if shape else 1
            if not any_sharded and mesh_ndev > 1 \
                    and n_elem >= self.large_param_threshold:
                mb = n_elem * 4 / 2**20
                report.add(Finding(
                    "replicated-large-param", Severity.WARNING,
                    f"param {name!r} ({n_elem:,} elems, ~{mb:.0f} MiB fp32) "
                    f"is fully replicated across {mesh_ndev} devices",
                    node=name,
                    fix_hint="add a sharding rule for it (e.g. shard the "
                             "output dim over 'tp')"))
        return report

    def lint_params(self, params) -> Report:
        """Convenience: accept an iterable of gluon Parameters."""
        shapes = {}
        for p in params:
            if getattr(p, "shape", None):
                shapes[p.name] = tuple(p.shape)
        return self.lint(shapes)
