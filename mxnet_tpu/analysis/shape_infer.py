"""Shape/dtype pre-flight — the one inference engine for the whole stack.

This is the NNVM ``InferShape``/``InferType`` analog: a topo-order walk
that evaluates every node through ``jax.eval_shape`` over the same pure op
functions the executor jits, deriving auto-created parameter shapes from
the per-op rules in ``symbol.symbol._param_shape_rules``.

Three consumers share it so they can never disagree:

- ``Symbol.infer_shape``/``infer_type`` (raise mode: first failure raises a
  node-attributed :class:`GraphAnalysisError`);
- the ``shape-preflight`` lint pass (collect mode: failures become
  ``Finding``s and the walk continues past them);
- ``visualization.print_summary`` (per-node output shapes for the table).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

import numpy as np

from ..base import GraphAnalysisError
from .findings import Finding, Severity

__all__ = ["InferResult", "infer_graph"]


@dataclass
class InferResult:
    shapes: Dict[str, tuple] = field(default_factory=dict)
    dtypes: Dict[str, Any] = field(default_factory=dict)
    out_shapes: List[Optional[tuple]] = field(default_factory=list)
    out_dtypes: List[Any] = field(default_factory=list)
    node_out: Dict[int, Any] = field(default_factory=dict)    # id(node) -> shape|[shapes]
    node_dtype: Dict[int, Any] = field(default_factory=dict)  # id(node) -> dtype|[dtypes]
    node_in: Dict[int, List[Optional[tuple]]] = field(default_factory=dict)
    findings: List[Finding] = field(default_factory=list)
    failed: Set[int] = field(default_factory=set)


def _var_dtype(node, known_dtypes):
    if known_dtypes and node._name in known_dtypes:
        return np.dtype(known_dtypes[node._name])
    d = node._attrs.get("__dtype__")
    if d is not None:
        try:
            from ..base import dtype_np

            return np.dtype(dtype_np(str(d)))
        except Exception:
            try:
                return np.dtype(str(d))
            except TypeError:
                pass
    return np.dtype(np.float32)


def infer_graph(sym, known_shapes: Dict[str, tuple],
                known_dtypes: Optional[Dict[str, Any]] = None,
                collect: bool = False,
                use_hint_cache: bool = False) -> InferResult:
    """Walk ``sym`` in topo order inferring every node's output shape/dtype.

    collect=False: raise :class:`GraphAnalysisError` at the first failure,
    naming the node, its op, and its input shapes.
    collect=True: record failures as error findings and keep walking
    (downstream nodes with unknown inputs are skipped, not re-reported).
    use_hint_cache: reuse/populate per-node ``_hint_shape`` memos. ONLY
    valid when no explicit known_shapes/known_dtypes are given (the
    ``Symbol.shape`` path): cached values are derived purely from
    ``Variable(shape=...)`` hints, which are fixed at construction, so a
    repeated walk — e.g. per-layer ``x.shape`` reads while tracing a deep
    net — skips the eval_shape of every already-seen prefix node.
    """
    import jax

    from ..ops import get_op, has_op
    from ..ops.registry import coerce_kwargs
    from ..symbol.symbol import _param_shape_rules, op_input_names

    res = InferResult()
    res.shapes = {k: tuple(v) for k, v in known_shapes.items()}
    if known_dtypes:
        res.dtypes = {k: np.dtype(v) for k, v in known_dtypes.items()}

    def fail(rule_id, msg, node_name, op, in_shapes=None, fix_hint=None):
        if not collect:
            raise GraphAnalysisError(msg, node=node_name, op=op,
                                     rule_id=rule_id, input_shapes=in_shapes)
        res.findings.append(Finding(rule_id, Severity.ERROR, msg,
                                    node=node_name, op=op, fix_hint=fix_hint,
                                    details={"input_shapes": in_shapes}))

    use_hint_cache = use_hint_cache and not known_shapes and not known_dtypes
    for node in sym._topo():
        if use_hint_cache and "_hint_shape" in node.__dict__:
            s, d = node._hint_shape, node._hint_dtype
            res.node_out[id(node)] = s
            res.node_dtype[id(node)] = d
            if node._op is None:
                res.shapes[node._name] = s
                res.dtypes[node._name] = d
            continue
        if node._op is None:
            if node._name not in res.shapes and "__shape__" in node._attrs:
                res.shapes[node._name] = tuple(node._attrs["__shape__"])
            if node._name in res.shapes:
                res.node_out[id(node)] = res.shapes[node._name]
                dt = _var_dtype(node, known_dtypes)
                res.dtypes[node._name] = dt
                res.node_dtype[id(node)] = dt
            continue
        if node._op == "_group":
            continue
        inline_opdef = getattr(node, "_opdef", None)  # symbol.invoke_fn
        if inline_opdef is None and not has_op(node._op):
            fail("unknown-op",
                 f"operator {node._op!r} is not in the op registry",
                 node._name, node._op,
                 fix_hint="check the op name / load a graph exported by "
                          "this framework version")
            res.failed.add(id(node))
            continue
        opdef = inline_opdef or get_op(node._op)
        kwargs = coerce_kwargs({k2: v for k2, v in node._attrs.items()
                                if not k2.startswith("__")})
        input_names = op_input_names(opdef)
        # primary input shape drives the parameter auto-shape rules
        primary = None
        for i in node._inputs:
            s = res.node_out.get(id(i._base()))
            if s is not None:
                if i._index is not None and isinstance(s, list):
                    s = s[i._index]
                primary = s
                break
        in_shapes: List[Optional[tuple]] = []
        in_dtypes: List[Any] = []
        skip = False
        for pos, i in enumerate(node._inputs):
            base = i._base()
            s = res.node_out.get(id(base))
            d = res.node_dtype.get(id(base))
            if s is not None and i._index is not None and isinstance(s, list):
                s = s[i._index]
                d = d[i._index] if isinstance(d, list) else d
            if s is None and base._op is None:
                arg = input_names[pos] if pos < len(input_names) else None
                s = _param_shape_rules(node._op, primary, kwargs, arg) \
                    if primary is not None and arg else None
                if s is None:
                    fail("missing-shape",
                         f"cannot infer shape of {base._name!r} (input "
                         f"{arg!r} of {node._op}); provide it explicitly",
                         base._name, node._op,
                         fix_hint=f"pass {base._name}=<shape> to infer_shape/"
                                  "bind, or set shape= on the Variable")
                    skip = True
                    break
                res.shapes[base._name] = tuple(s)
                res.node_out[id(base)] = tuple(s)
                d = _var_dtype(base, known_dtypes)
                res.dtypes[base._name] = d
                res.node_dtype[id(base)] = d
            if s is None:
                # upstream failure already reported; don't cascade
                skip = True
                break
            in_shapes.append(tuple(s))
            in_dtypes.append(np.dtype(d) if d is not None else
                             np.dtype(np.float32))
        res.node_in[id(node)] = in_shapes
        if skip:
            res.failed.add(id(node))
            continue
        avals = [jax.ShapeDtypeStruct(s, d)
                 for s, d in zip(in_shapes, in_dtypes)]
        try:
            out = jax.eval_shape(lambda *a: opdef.fn(*a, **kwargs), *avals)
        except Exception as e:
            shown = ", ".join(f"{n}:{s}" for n, s in
                              zip([i._base()._name for i in node._inputs],
                                  in_shapes))
            fail("shape-mismatch",
                 f"shape inference failed at {node._op} ({node._name}): "
                 f"inputs [{shown}] attrs {kwargs or '{}'}: {e}",
                 node._name, node._op, in_shapes=in_shapes,
                 fix_hint="fix the input shapes or the op attrs shown above")
            res.failed.add(id(node))
            continue
        if isinstance(out, (list, tuple)):
            res.node_out[id(node)] = [tuple(o.shape) for o in out]
            res.node_dtype[id(node)] = [np.dtype(o.dtype) for o in out]
        else:
            res.node_out[id(node)] = tuple(out.shape)
            res.node_dtype[id(node)] = np.dtype(out.dtype)

    if use_hint_cache:
        for node in sym._topo():
            if id(node) in res.node_out and id(node) not in res.failed \
                    and "_hint_shape" not in node.__dict__:
                node._hint_shape = res.node_out[id(node)]
                node._hint_dtype = res.node_dtype.get(id(node))

    # ---- head outputs -------------------------------------------------
    if sym._op == "_group":
        heads = [(s._base(), s._index) for s in sym._inputs]
    else:
        heads = [(sym._base(), sym._index)]
    for base, index in heads:
        s = res.node_out.get(id(base))
        d = res.node_dtype.get(id(base))
        if isinstance(s, list):
            if index is not None:
                res.out_shapes.append(s[index])
                res.out_dtypes.append(d[index] if isinstance(d, list) else d)
            else:
                res.out_shapes.extend(s)
                res.out_dtypes.extend(d if isinstance(d, list)
                                      else [d] * len(s))
        else:
            res.out_shapes.append(s)
            res.out_dtypes.append(d)
    return res
