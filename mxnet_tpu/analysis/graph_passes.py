"""GraphLinter — pass-based static analysis over Symbol graphs.

The NNVM-pass analog for this framework: each pass is a pure function
``(GraphView, LintContext) -> [Finding]`` registered under a name, and
:class:`GraphLinter` runs a configurable subset. Rules are documented in
``docs/ANALYSIS.md``; every rule has a stable id used for filtering and
suppression.

Rule ids shipped here:

- ``duplicate-name``       two distinct nodes share a name (error)
- ``dead-node``            node unreachable from any head (warning)
- ``unused-argument``      variable consumed by nothing (warning)
- ``unknown-op``           op missing from the registry (error)
- ``shape-mismatch``       eval_shape pre-flight failed at a node (error)
- ``missing-shape``        variable shape not inferable (error)
- ``zero-size-reduction``  reduction over a zero-size axis -> NaN/-inf (error)
- ``nondiff-on-grad-path`` non-differentiable op between params and loss (warning)
- ``log-of-softmax``       log(softmax(x)) idiom, catastrophic underflow (warning)
- ``exp-on-raw-input``     exp applied to unnormalized graph input (info)
- ``high-fanout``          one value consumed by many ops; remat hazard (info)
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from .findings import Finding, Report, Severity
from .graph import GraphView, NodeInfo

__all__ = ["GraphLinter", "graph_pass", "list_passes", "LintContext"]

PASS_REGISTRY: Dict[str, Callable] = {}
PASS_RULES: Dict[str, tuple] = {}

# op-name fallbacks so JSON graphs from the reference (whose ops carry no
# OpDef tags) still hit the numerics/reduction rules
_SOFTMAX_OPS = {"softmax", "Softmax", "SoftmaxActivation", "log_softmax"}
_LOG_OPS = {"log", "log2", "log10"}  # log1p is the stabilized idiom
_EXP_OPS = {"exp"}
# only reductions WITHOUT an identity on empty axes (mean -> NaN,
# max/min -> ±inf); sum/prod/norm are well-defined there (see ops/reduce.py)
_REDUCE_OPS = {"mean", "max", "min", "max_axis", "min_axis"}


def graph_pass(name: str, rules: tuple = ()):
    """Register a lint pass under ``name`` (see docs/ANALYSIS.md to add one)."""

    def deco(fn):
        PASS_REGISTRY[name] = fn
        PASS_RULES[name] = rules
        return fn

    return deco


def list_passes() -> Dict[str, tuple]:
    return dict(PASS_RULES)


class LintContext:
    """Per-run state shared by passes: input shapes, options, lazy infer."""

    def __init__(self, shapes: Optional[Dict[str, tuple]] = None,
                 dtypes: Optional[Dict[str, Any]] = None, **options):
        self.shapes = dict(shapes or {})
        self.dtypes = dict(dtypes or {})
        self.options = options
        self._infer = None

    def option(self, key, default=None):
        return self.options.get(key, default)

    def infer(self, view: GraphView):
        """Collect-mode shape pre-flight, run at most once per lint."""
        if self._infer is None and view.symbol is not None:
            from .shape_infer import infer_graph

            self._infer = infer_graph(view.symbol, self.shapes,
                                      self.dtypes or None, collect=True)
        return self._infer


def _op_tags(op: Optional[str]) -> tuple:
    from ..ops import has_op, get_op

    if op and has_op(op):
        return tuple(getattr(get_op(op), "tags", ()) or ())
    return ()


def _is(node: NodeInfo, tag: str, names: set) -> bool:
    return node.op in names or tag in _op_tags(node.op)


# ---------------------------------------------------------------------------
# passes
# ---------------------------------------------------------------------------

@graph_pass("structure", rules=("duplicate-name", "dead-node",
                                "unused-argument"))
def _structure_pass(view: GraphView, ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    seen: Dict[str, int] = {}
    for n in view.nodes:
        if n.name in seen:
            other = view.nodes[seen[n.name]]
            out.append(Finding(
                "duplicate-name", Severity.ERROR,
                f"nodes #{seen[n.name]} ({other.op or 'variable'}) and "
                f"#{n.idx} ({n.op or 'variable'}) both named {n.name!r}; "
                "bind/arg_dict are name-keyed, one will shadow the other",
                node=n.name, op=n.op,
                fix_hint="give each op/Variable a unique name= "))
        else:
            seen[n.name] = n.idx
    live = view.reachable()
    heads = view.head_indices()
    for n in view.nodes:
        if n.op == "_group":  # head grouping marker, not a real node
            continue
        if n.is_variable:
            # a variable only counts as used if something LIVE consumes it
            consumers = [c for c, _ in view.consumers[n.idx]
                         if not view.heads or c in live
                         or view.nodes[c].op == "_group"]
            if not consumers and n.idx not in heads:
                out.append(Finding(
                    "unused-argument", Severity.WARNING,
                    f"argument {n.name!r} is consumed by nothing in the "
                    "live graph and is not an output; it still occupies an "
                    "arg slot at bind time",
                    node=n.name,
                    fix_hint="remove the unused Variable"))
        elif n.idx not in live and view.heads:
            out.append(Finding(
                "dead-node", Severity.WARNING,
                f"node {n.name!r} ({n.op}) is unreachable from the graph "
                "heads; it will never execute",
                node=n.name, op=n.op,
                fix_hint="drop it from the graph json, or add it to heads"))
    return out


@graph_pass("registry", rules=("unknown-op",))
def _registry_pass(view: GraphView, ctx: LintContext) -> List[Finding]:
    from ..ops import has_op

    out = []
    for n in view.op_nodes():
        if getattr(n.sym, "_opdef", None) is not None:
            continue  # invoke_fn node: OpDef carried inline, not registered
        if not has_op(n.op):
            out.append(Finding(
                "unknown-op", Severity.ERROR,
                f"operator {n.op!r} (node {n.name!r}) is not in the op "
                "registry; bind would raise NotImplementedError",
                node=n.name, op=n.op,
                fix_hint="check the op name, or port the op into "
                         "mxnet_tpu/ops/"))
    return out


@graph_pass("shape-preflight", rules=("shape-mismatch", "missing-shape",
                                      "zero-size-reduction", "unknown-op"))
def _shape_pass(view: GraphView, ctx: LintContext) -> List[Finding]:
    if view.symbol is None:
        return []
    has_hints = any("__shape__" in n.attrs for n in view.variables())
    if not ctx.shapes and not has_hints:
        return []  # nothing to anchor inference; bind-time lint supplies shapes
    res = ctx.infer(view)
    out = list(res.findings)
    # zero-size reductions: legal to trace, NaN/-inf at run time
    id_to_shape = res.node_out
    for n in view.op_nodes():
        if n.sym is None or not _is(n, "reduction", _REDUCE_OPS):
            continue
        in_shapes = res.node_in.get(id(n.sym)) or []
        if not in_shapes or in_shapes[0] is None:
            continue
        shape = in_shapes[0]
        kw = n.kwargs()
        axis = kw.get("axis", None)
        if axis is None:
            reduced = range(len(shape))
        else:
            axes = axis if isinstance(axis, (tuple, list)) else (axis,)
            try:
                reduced = [int(a) % max(len(shape), 1) for a in axes]
            except (TypeError, ValueError):
                continue
        if any(shape[a] == 0 for a in reduced if a < len(shape)):
            out.append(Finding(
                "zero-size-reduction", Severity.ERROR,
                f"{n.op} ({n.name!r}) reduces over a zero-size axis of "
                f"input shape {shape}; mean/max produce NaN/-inf at run time",
                node=n.name, op=n.op,
                fix_hint="guard the empty case or fix the upstream shape"))
    return out


@graph_pass("grad-path", rules=("nondiff-on-grad-path",))
def _grad_path_pass(view: GraphView, ctx: LintContext) -> List[Finding]:
    """Non-differentiable ops (OpDef.differentiable=False) that sit between
    trainable parameters and the graph outputs block/zero gradients."""
    from ..ops import get_op, has_op

    param_names = ctx.option("param_names")
    suffixes = ("weight", "bias", "gamma", "beta")

    def is_param(n: NodeInfo) -> bool:
        if not n.is_variable or n.attrs.get("__aux__"):
            return False
        if param_names is not None:
            return n.name in param_names
        return n.name.endswith(suffixes)

    depends_on_param = [False] * len(view.nodes)
    out: List[Finding] = []
    for n in view.nodes:  # topo order for Symbol views; JSON is topo too
        if n.is_variable:
            depends_on_param[n.idx] = is_param(n)
            continue
        dep = any(depends_on_param[src] for src, _ in n.inputs)
        depends_on_param[n.idx] = dep
        if dep and n.op != "_group" and has_op(n.op) \
                and not get_op(n.op).differentiable:
            out.append(Finding(
                "nondiff-on-grad-path", Severity.WARNING,
                f"{n.op} ({n.name!r}) is non-differentiable but depends on "
                "trainable parameters; backward will stop or zero gradients "
                "through it",
                node=n.name, op=n.op,
                fix_hint="move it off the loss path (metrics/postprocess) "
                         "or use a differentiable surrogate"))
    return out


@graph_pass("numerics", rules=("log-of-softmax", "exp-on-raw-input"))
def _numerics_pass(view: GraphView, ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    for n in view.op_nodes():
        if _is(n, "log", _LOG_OPS):
            for src, _o in n.inputs:
                srcn = view.nodes[src]
                if srcn.op is not None and _is(srcn, "softmax", _SOFTMAX_OPS) \
                        and srcn.op != "log_softmax":
                    out.append(Finding(
                        "log-of-softmax", Severity.WARNING,
                        f"log ({n.name!r}) applied to {srcn.op} "
                        f"({srcn.name!r}): underflows to -inf for "
                        "confident predictions",
                        node=n.name, op=n.op,
                        fix_hint="use log_softmax (one fused, stabilized "
                                 "op) or SoftmaxCrossEntropy-style loss"))
        if _is(n, "exp", _EXP_OPS):
            for src, _o in n.inputs:
                srcn = view.nodes[src]
                if srcn.is_variable and not srcn.name.endswith(
                        ("weight", "bias", "gamma", "beta")):
                    out.append(Finding(
                        "exp-on-raw-input", Severity.INFO,
                        f"exp ({n.name!r}) applied directly to graph input "
                        f"{srcn.name!r}; unbounded inputs overflow to inf "
                        "in fp32 above ~88",
                        node=n.name, op=n.op,
                        fix_hint="subtract a running max / clip / normalize "
                                 "before exponentiating"))
    return out


@graph_pass("fanout", rules=("high-fanout",))
def _fanout_pass(view: GraphView, ctx: LintContext) -> List[Finding]:
    threshold = int(ctx.option("fanout_threshold", 8))
    out: List[Finding] = []
    for n in view.op_nodes():
        consumers = view.consumers[n.idx]
        if len(consumers) >= threshold:
            out.append(Finding(
                "high-fanout", Severity.INFO,
                f"{n.op} ({n.name!r}) output feeds {len(consumers)} "
                "consumers; its activation is live across all of them and "
                "backward recomputes/holds it for each",
                node=n.name, op=n.op,
                fix_hint="consider remat (ShardedTrainer(remat=True)) or "
                         "restructuring the fan-out"))
    return out


# ---------------------------------------------------------------------------


class GraphLinter:
    """Run a set of passes over a Symbol or a ``tojson()`` graph.

    ::

        report = GraphLinter().lint(sym, shapes={"data": (2, 3, 32, 32)})
        report.raise_if_errors()

    ``passes`` selects a subset by name; ``options`` are forwarded to the
    :class:`LintContext` (e.g. ``fanout_threshold=4``,
    ``param_names={...}``, ``disable={"high-fanout"}``).
    """

    def __init__(self, passes: Optional[List[str]] = None, **options):
        unknown = set(passes or ()) - set(PASS_REGISTRY)
        if unknown:
            raise ValueError(f"unknown lint passes {sorted(unknown)}; "
                             f"available: {sorted(PASS_REGISTRY)}")
        self.passes = list(passes) if passes is not None \
            else list(PASS_REGISTRY)
        self.options = options

    def lint(self, graph, shapes: Optional[Dict[str, tuple]] = None,
             dtypes: Optional[Dict[str, Any]] = None,
             **shape_kwargs) -> Report:
        all_shapes = dict(shapes or {})
        all_shapes.update({k: tuple(v) for k, v in shape_kwargs.items()})
        if isinstance(graph, (str, dict)):
            view = GraphView.from_json(graph)
        else:
            view = GraphView.from_symbol(graph)
        ctx = LintContext(shapes=all_shapes, dtypes=dtypes, **self.options)
        disable = set(self.options.get("disable") or ())
        report = Report()
        seen = set()
        for name in self.passes:
            for f in PASS_REGISTRY[name](view, ctx):
                if f.rule_id in disable:
                    continue
                key = (f.rule_id, f.node, f.message)
                if key in seen:  # e.g. unknown-op via registry + preflight
                    continue
                seen.add(key)
                report.add(f)
        return report
